"""Native row-group fast path: parity oracle, zero-copy contract, dictionary
shipping, mixed-dialect scans, and fault discipline.

The fast path (exec/io.py ``_native_rg_scan``) decodes every surviving
(file × row group × column) chunk in parallel straight into one
bucket-padded buffer per column. Its contract is byte-identity with the
pyarrow path under every dialect dimension the decoder claims — and an
accounted fallback everywhere else. The whole module rides the ``native``
tier-1 marker and skips cleanly when the C toolchain is absent.
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.dataset as pads
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu import native
from hyperspace_tpu.exec import io as hio
from hyperspace_tpu.exec.batch import DictBackedArray
from hyperspace_tpu.exec.io import clear_io_cache, read_parquet_batch
from hyperspace_tpu.obs.metrics import REGISTRY
from hyperspace_tpu.plan.expr import col, lit

pytestmark = [
    pytest.mark.native,
    pytest.mark.skipif(
        not native.is_available(), reason="native toolchain unavailable"
    ),
]

CODECS = ["NONE", "SNAPPY", "GZIP", "ZSTD"]


@pytest.fixture(autouse=True)
def _fresh_io_state():
    """Cache entries and the module decode knobs are process-global; pin the
    defaults around every test so legs cannot see each other's state."""
    clear_io_cache()
    hio.set_native_options(enabled=True, rowgroup=True, max_dict_entries=4096)
    yield
    clear_io_cache()
    hio.set_native_options(enabled=True, rowgroup=True, max_dict_entries=4096)


def _oracle_table(n=2400, null_runs=False):
    """Every dtype the decoder claims; ``null_runs`` adds long NULL stretches
    (whole row groups of nulls) on top of scattered ones."""
    rng = np.random.default_rng(23)

    def _mask(period, run):
        m = np.zeros(n, dtype=bool)
        if null_runs:
            m[(np.arange(n) // run) % period == 0] = True  # long runs
        m[rng.integers(0, n, n // 17)] = True  # scattered
        return m

    def _null(arr, m):
        return pa.array([None if m[i] else v for i, v in enumerate(arr.tolist())])

    i32 = rng.integers(-(2**31), 2**31 - 1, n).astype(np.int32)
    i64 = rng.integers(-(10**12), 10**12, n).astype(np.int64)
    f32 = rng.standard_normal(n).astype(np.float32)
    f64 = rng.standard_normal(n)
    date = (np.datetime64("2021-01-01") + rng.integers(0, 2000, n).astype("timedelta64[D]"))
    ts = (np.datetime64("2020-01-01") + rng.integers(0, 10**6, n).astype("timedelta64[s]"))
    s = [f"v{i % 311}" for i in range(n)]
    cols = {
        "k": pa.array(np.arange(n, dtype=np.int64)),  # sorted prune key
        "i32": pa.array(i32),
        "i64": pa.array(i64),
        "f32": pa.array(f32),
        "f64": pa.array(f64),
        "date": pa.array(date.astype("datetime64[D]")),
        "ts": pa.array(ts),
        "s": pa.array(s),
    }
    if null_runs:
        cols["ni"] = _null(i64, _mask(3, 300))
        cols["nf"] = _null(f64, _mask(4, 300))
        cols["ns"] = pa.array(
            [None if m else v for m, v in zip(_mask(2, 300).tolist(), s)]
        )
    return pa.table(cols)


def _assert_columns_equal(got, exp, label=""):
    assert set(got) == set(exp), label
    for c in got:
        a, b = np.asarray(got[c]), np.asarray(exp[c])
        assert a.dtype == b.dtype, (label, c, a.dtype, b.dtype)
        if a.dtype == object:
            assert len(a) == len(b), (label, c)
            for x, y in zip(a, b):
                assert (x is None and y is None) or x == y, (label, c)
        else:
            # NaN/NaT compare equal under assert_array_equal
            np.testing.assert_array_equal(a, b, err_msg=f"{label}:{c}")


def _two_leg_read(files, columns=None, predicate=None):
    """The oracle harness: the same read with the fast path on and with
    native decode entirely off (pure pyarrow), caches cleared between."""
    clear_io_cache()
    hio.set_native_options(enabled=True, rowgroup=True)
    fast = read_parquet_batch(list(files), columns, predicate=predicate)
    clear_io_cache()
    hio.set_native_options(enabled=False)
    slow = read_parquet_batch(list(files), columns, predicate=predicate)
    return fast, slow


class TestOracleMatrix:
    @pytest.mark.parametrize("codec", CODECS)
    def test_all_dtypes(self, tmp_path, codec):
        t = _oracle_table()
        p = str(tmp_path / f"m_{codec}.parquet")
        pq.write_table(t, p, compression=codec, row_group_size=500)
        fast, slow = _two_leg_read([p], t.column_names)
        _assert_columns_equal(fast, slow, codec)

    @pytest.mark.parametrize("codec", CODECS)
    def test_null_runs(self, tmp_path, codec):
        t = _oracle_table(null_runs=True)
        p = str(tmp_path / f"n_{codec}.parquet")
        pq.write_table(t, p, compression=codec, row_group_size=400)
        fast, slow = _two_leg_read([p], t.column_names)
        _assert_columns_equal(fast, slow, codec)

    @pytest.mark.parametrize("codec", CODECS)
    def test_pruned_rowgroup_subsets(self, tmp_path, codec):
        t = _oracle_table()
        files = []
        for i in range(2):
            p = str(tmp_path / f"p{i}_{codec}.parquet")
            pq.write_table(t, p, compression=codec, row_group_size=400)
            files.append(p)
        # k is sorted 0..n: the predicate survives exactly rows < 900
        # (row groups 0-2 of 6 per file)
        pred = col("k") < lit(900)
        fast, slow = _two_leg_read(files, t.column_names, predicate=pred)
        _assert_columns_equal(fast, slow, codec)
        assert np.asarray(fast["k"]).max() < 1200  # pruning actually dropped RGs

    def test_multi_file_concat(self, tmp_path):
        t = _oracle_table()
        files = []
        for i in range(3):
            p = str(tmp_path / f"c{i}.parquet")
            pq.write_table(t.slice(i * 800, 800), p, compression="SNAPPY",
                           row_group_size=300)
            files.append(p)
        fast, slow = _two_leg_read(files, t.column_names)
        _assert_columns_equal(fast, slow, "concat")
        assert len(np.asarray(fast["k"])) == 2400

    def test_decode_metrics_and_trace(self, tmp_path):
        from hyperspace_tpu.exec import trace

        t = _oracle_table()
        p = str(tmp_path / "metrics.parquet")
        pq.write_table(t, p, compression="ZSTD", row_group_size=600)
        decoded = REGISTRY.counter("hs_native_decode_total", codec="zstd").value
        nbytes = REGISTRY.counter("hs_native_decode_bytes_total").value
        clear_io_cache()
        with trace.recording() as events:
            read_parquet_batch([p], t.column_names)
        assert ("decode", "native-rg") in events
        assert REGISTRY.counter("hs_native_decode_total", codec="zstd").value == (
            decoded + 4 * len(t.column_names)  # 4 row groups x every column
        )
        assert REGISTRY.counter("hs_native_decode_bytes_total").value > nbytes


class TestZeroCopy:
    def test_decode_buffer_ships_pointer_identical(self, tmp_path):
        """The exact numpy buffer the C decoder wrote is what device staging
        pads to — no host copy between decode and device_put."""
        from hyperspace_tpu.exec import device as D

        hio.set_staging_pad(8)
        t = pa.table({"a": pa.array(np.arange(1000, dtype=np.int64)),
                      "x": pa.array(np.arange(1000, dtype=np.float64))})
        p = str(tmp_path / "zc.parquet")
        pq.write_table(t, p, compression="NONE", row_group_size=250)
        b = read_parquet_batch([p], ["a", "x"])
        for c, fill in (("a", 0), ("x", np.nan)):
            arr = b[c]
            assert arr.base is not None and arr.base.shape == (4096,), c
            enc, _codec = D.encode_column(arr)
            assert enc is arr, c  # encode is a no-op view, not a copy
            padded = D._pad_to_bucket(enc, 8, fill)
            assert padded is arr.base, c  # staging adopts the decoder's buffer

    def test_adoption_rejects_garbage_tail(self):
        """A coincidentally-shaped view whose base tail is NOT the fill value
        must be copied, never adopted — the tail would leak into the device
        column."""
        from hyperspace_tpu.exec import device as D

        base = np.full(4096, 7, dtype=np.int64)  # tail != 0
        view = base[:1000]
        padded = D._pad_to_bucket(view, 8, 0)
        assert padded is not base
        assert (padded[1000:] == 0).all()


class TestDictionaryShipping:
    def test_strings_come_back_dict_backed(self, tmp_path):
        t = pa.table({"s": pa.array([f"cat{i % 7}" for i in range(2000)])})
        p = str(tmp_path / "d.parquet")
        pq.write_table(t, p, compression="SNAPPY", row_group_size=500)
        b = read_parquet_batch([p], ["s"])
        arr = b["s"]
        assert isinstance(arr, DictBackedArray)
        assert arr.hs_dict_codes is not None and arr.hs_dict_codes.dtype == np.int32
        assert sorted(arr.hs_dict_uniques) == sorted({f"cat{i}" for i in range(7)})
        # expanded values equal the codes gathered through the dictionary
        exp = arr.hs_dict_uniques[arr.hs_dict_codes]
        assert all(a == b_ for a, b_ in zip(arr, exp))

    def test_max_dict_entries_gate(self, tmp_path):
        t = pa.table({"s": pa.array([f"cat{i % 7}" for i in range(1000)])})
        p = str(tmp_path / "g.parquet")
        pq.write_table(t, p, compression="NONE", row_group_size=500)
        hio.set_native_options(max_dict_entries=3)  # dict of 7 > 3: no shipping
        b = read_parquet_batch([p], ["s"])
        assert not isinstance(b["s"], DictBackedArray)
        assert b["s"][13] == "cat6"

    def test_dict_expand_on_device_matches_and_passes_contract(self, tmp_path):
        """Decode → stage → fused on-device expansion, end to end: a device
        filter over a dict-shipped string column masks identically to host
        evaluation, dispatches the dict-expand program, and violates no
        registered HLO contract (HS_CHECK_HLO semantics)."""
        from hyperspace_tpu.check import hlo_lint
        from hyperspace_tpu.exec import device as D
        from hyperspace_tpu.plan.expr import as_bool_mask

        rng = np.random.default_rng(5)
        t = pa.table({
            "s": pa.array([f"cat{j % 5}" for j in range(3000)]),
            "a": pa.array(rng.integers(0, 3000, 3000).astype(np.int64)),
        })
        p = str(tmp_path / "f.parquet")
        pq.write_table(t, p, row_group_size=500)

        hlo_lint.reset_runtime_state()
        sess = hst.Session(conf={hst.keys.CHECK_HLO_ENABLED: True})
        batch = read_parquet_batch([p], ["s", "a"])
        assert isinstance(batch["s"], DictBackedArray)  # shipped, not strings

        cond = (col("s") == lit("cat3")) & (col("a") >= lit(1000))
        before = REGISTRY.counter(
            "hs_device_dispatches_total", program="dict-expand"
        ).value
        mask = D.device_filter_mask(sess, batch, cond)
        after = REGISTRY.counter(
            "hs_device_dispatches_total", program="dict-expand"
        ).value
        assert after == before + 1  # the fused expansion actually ran
        assert hlo_lint.runtime_violations() == []

        exp = as_bool_mask(cond.eval(batch))
        np.testing.assert_array_equal(np.asarray(mask), exp)
        assert exp.sum() > 0  # the predicate selected something real


class TestMixedDialects:
    def test_native_plus_schema_evolved(self, tmp_path):
        """One native-dialect file + one schema-evolved file (missing column)
        in the same scan: the native file takes the fast path, the evolved one
        decodes through pyarrow against the unified schema, and the result is
        identical to a pure dataset read."""
        t1 = pa.table({"a": pa.array(np.arange(1000, dtype=np.int64)),
                       "b": pa.array(np.arange(1000, dtype=np.float64))})
        t2 = pa.table({"a": pa.array(np.arange(1000, 1600, dtype=np.int64))})
        p1, p2 = str(tmp_path / "full.parquet"), str(tmp_path / "old.parquet")
        pq.write_table(t1, p1, row_group_size=250)
        pq.write_table(t2, p2, row_group_size=250)

        evolved_before = REGISTRY.counter(
            "hs_native_fallback_total", reason="schema-evolved"
        ).value
        got = read_parquet_batch([p1, p2], ["a", "b"])
        assert REGISTRY.counter(
            "hs_native_fallback_total", reason="schema-evolved"
        ).value == evolved_before + 1

        ds = pads.dataset([p1, p2], format="parquet")
        exp = ds.to_table(columns=["a", "b"])
        assert np.array_equal(got["a"], exp["a"].to_numpy())
        # the missing column null-fills: dataset semantics exactly
        exp_b = exp["b"].to_numpy(zero_copy_only=False)
        assert got["b"].dtype == exp_b.dtype
        np.testing.assert_array_equal(got["b"], exp_b)

    def test_unsupported_file_rides_along(self, tmp_path):
        """A same-schema file outside the native dialect (unsupported codec)
        must not poison the scan: it falls back per file, counted, and the
        batch is still exactly right."""
        t = pa.table({"a": pa.array(np.arange(800, dtype=np.int64))})
        p1, p2 = str(tmp_path / "n.parquet"), str(tmp_path / "lz4.parquet")
        pq.write_table(t, p1, compression="NONE")
        try:
            pq.write_table(t, p2, compression="LZ4")
        except Exception:
            pytest.skip("pyarrow built without LZ4")
        dialect_before = REGISTRY.counter(
            "hs_native_fallback_total", reason="dialect"
        ).value
        got = read_parquet_batch([p1, p2], ["a"])
        assert np.array_equal(
            got["a"], np.concatenate([np.arange(800), np.arange(800)])
        )
        assert (
            REGISTRY.counter("hs_native_fallback_total", reason="dialect").value
            > dialect_before
        )


@pytest.mark.faults
class TestNativeFaultSeam:
    @pytest.fixture(autouse=True)
    def _clean_faults(self):
        from hyperspace_tpu.reliability.faults import FAULTS

        yield
        FAULTS.clear()

    def _write(self, tmp_path):
        t = pa.table({"a": pa.array(np.arange(1200, dtype=np.int64))})
        p = str(tmp_path / "f.parquet")
        pq.write_table(t, p, row_group_size=300)
        return p

    def test_corrupt_fault_surfaces_typed_and_strikes_quarantine(self, tmp_path):
        from hyperspace_tpu.reliability.degrade import QUARANTINE
        from hyperspace_tpu.reliability.errors import CorruptDataError
        from hyperspace_tpu.reliability.faults import FaultRule, fault_scope

        # quarantine attributes strikes through the index layout
        # <system.path>/<name>/...: put the file under one
        idx = tmp_path / "indexes" / "idx1"
        idx.mkdir(parents=True)
        p = self._write(idx)
        hst.Session(conf={
            hst.keys.SYSTEM_PATH: str(tmp_path / "indexes"),
            hst.keys.RELIABILITY_QUARANTINE_ENABLED: True,
        })
        try:
            with fault_scope(FaultRule("io.decode", "corrupt", nth=1)):
                with pytest.raises(CorruptDataError):
                    read_parquet_batch([p], ["a"])
            assert QUARANTINE.local_strikes().get("idx1", 0) >= 1
        finally:
            QUARANTINE.enabled = False
            QUARANTINE._breakers = {}

    def test_transient_fault_falls_back_without_wrong_answer(self, tmp_path):
        from hyperspace_tpu.reliability.faults import FaultRule, fault_scope

        p = self._write(tmp_path)
        swallowed = REGISTRY.counter("hs_native_fallback_total", reason="io-error").value
        with fault_scope(FaultRule("io.decode", "transient", nth=1)):
            got = read_parquet_batch([p], ["a"])
        # the consumed fault is recorded, and the answer is still exact
        assert (
            REGISTRY.counter("hs_native_fallback_total", reason="io-error").value
            == swallowed + 1
        )
        assert np.array_equal(got["a"], np.arange(1200))


class TestKillSwitches:
    def test_env_kill_switch(self, tmp_path, monkeypatch):
        from hyperspace_tpu.exec import trace

        t = pa.table({"a": pa.array(np.arange(500, dtype=np.int64))})
        p = str(tmp_path / "k.parquet")
        pq.write_table(t, p, compression="NONE")
        monkeypatch.setenv("HS_NATIVE_RG", "0")
        with trace.recording() as events:
            got = read_parquet_batch([p], ["a"])
        assert ("decode", "native-rg") not in events
        assert np.array_equal(got["a"], np.arange(500))

    def test_conf_keys_reach_the_knobs(self, tmp_path):
        sess = hst.Session(conf={
            hst.keys.EXEC_IO_NATIVE_ROWGROUP: False,
            hst.keys.EXEC_IO_NATIVE_MAX_DICT: 17,
        })
        assert sess.conf.io_native_rowgroup is False
        assert sess.conf.io_native_max_dict_entries == 17
        assert hio._NATIVE_RG is False
        assert hio._MAX_DICT == 17
