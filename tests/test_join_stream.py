"""Streaming device join engine (round-11): pipelined bucketed SMJ,
broadcast hash join, fused post-join filter, shared build sides.

The contract under test everywhere: streamed ≡ materialized ≡ host pandas
oracle, for every join type, across NULL keys, composite keys, empty
buckets, and fallback boundaries — streaming is an execution strategy,
never a semantics change.
"""

import os
import time

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.plan import logical as L
from hyperspace_tpu.plan.expr import col

pytestmark = pytest.mark.join


# --------------------------------------------------------------------------
# harness
# --------------------------------------------------------------------------


def _mk_session(tmp_path, **conf):
    base = {hst.keys.SYSTEM_PATH: str(tmp_path / "indexes")}
    base.update(conf)
    sess = hst.Session(conf=base)
    hst.set_session(sess)
    return sess


def _write(d, table):
    os.makedirs(d, exist_ok=True)
    pq.write_table(pa.table(table), os.path.join(d, "p.parquet"))
    return d


def _norm(df: pd.DataFrame):
    return sorted(
        tuple(
            "NULL" if x is None or (isinstance(x, float) and x != x) else str(x)
            for x in row
        )
        for row in df.itertuples(index=False)
    )


def _counter(name) -> float:
    from hyperspace_tpu.obs.metrics import REGISTRY

    snap = REGISTRY.snapshot().get(name)
    if not snap:
        return 0.0
    return sum(s["value"] for s in snap["series"])


def _stream_concat(sess, plan) -> pd.DataFrame:
    from hyperspace_tpu.exec.executor import Executor

    chunks = [pd.DataFrame(c) for c in Executor(sess).execute_stream(plan)]
    return pd.concat(chunks, ignore_index=True) if chunks else pd.DataFrame()


@pytest.fixture()
def broadcast_sides(tmp_path):
    """A large probe side and a small broadcastable side, NULL keys in both."""
    rng = np.random.default_rng(11)
    n, m = 2500, 110
    lk = rng.integers(0, 60, n).astype(np.float64)
    lk[rng.random(n) < 0.04] = np.nan
    ldata = {
        "k": lk,
        "c": np.array([f"g{v}" for v in rng.integers(0, 6, n)]),
        "v": np.round(rng.standard_normal(n), 4),
    }
    rk = rng.integers(0, 70, m).astype(np.float64)
    rk[rng.random(m) < 0.04] = np.nan
    rdata = {
        "k2": rk,
        "c2": np.array([f"g{v}" for v in rng.integers(0, 7, m)]),
        "w": np.round(rng.standard_normal(m), 4),
    }
    _write(str(tmp_path / "l"), ldata)
    _write(str(tmp_path / "r"), rdata)
    sess = _mk_session(tmp_path)
    return sess, sess.read_parquet(str(tmp_path / "l")), sess.read_parquet(
        str(tmp_path / "r")
    ), pd.DataFrame(ldata), pd.DataFrame(rdata)


# --------------------------------------------------------------------------
# broadcast hash join: oracle equivalence
# --------------------------------------------------------------------------


class TestBroadcastOracle:
    @pytest.mark.parametrize("how", ["inner", "left", "right", "outer"])
    def test_streamed_materialized_oracle(self, broadcast_sides, how):
        sess, ldf, rdf, lpd, rpd = broadcast_sides
        q = ldf.join(rdf, on=col("k") == col("k2"), how=how)
        before = _counter("hs_join_broadcast_total")
        got_mat = pd.DataFrame(q.collect())
        assert _counter("hs_join_broadcast_total") > before, "broadcast path not taken"
        exp = lpd.merge(
            rpd, left_on="k", right_on="k2", how="outer" if how == "outer" else how
        )
        cols = list(exp.columns)
        assert sorted(got_mat.columns) == sorted(cols)
        assert _norm(got_mat[cols]) == _norm(exp)
        got_str = _stream_concat(sess, q.optimized_plan())
        assert _norm(got_str[cols]) == _norm(exp)

    @pytest.mark.parametrize("how", ["inner", "left", "right", "outer"])
    def test_composite_keys(self, broadcast_sides, how):
        sess, ldf, rdf, lpd, rpd = broadcast_sides
        q = ldf.join(
            rdf, on=(col("k") == col("k2")) & (col("c") == col("c2")), how=how
        )
        got = pd.DataFrame(q.collect())
        exp = lpd.merge(
            rpd,
            left_on=["k", "c"],
            right_on=["k2", "c2"],
            how="outer" if how == "outer" else how,
        )
        assert _norm(got[list(exp.columns)]) == _norm(exp)

    def test_no_match_join_is_typed_empty(self, tmp_path):
        _write(str(tmp_path / "l"), {"k": np.arange(50, dtype=np.int64), "v": np.ones(50)})
        _write(str(tmp_path / "r"), {"k2": np.arange(1000, 1010, dtype=np.int64), "w": np.ones(10)})
        sess = _mk_session(tmp_path)
        q = sess.read_parquet(str(tmp_path / "l")).join(
            sess.read_parquet(str(tmp_path / "r")), on=col("k") == col("k2")
        )
        got = q.collect()
        assert sorted(got) == ["k", "k2", "v", "w"]
        assert all(len(a) == 0 for a in got.values())

    def test_build_over_budget_falls_back(self, broadcast_sides):
        sess, ldf, rdf, lpd, rpd = broadcast_sides
        sess.conf.set(hst.keys.EXEC_JOIN_BROADCAST_MAX_BYTES, 16)
        try:
            before = _counter("hs_join_broadcast_total")
            q = ldf.join(rdf, on=col("k") == col("k2"), how="left")
            got = pd.DataFrame(q.collect())
            assert _counter("hs_join_broadcast_total") == before, "budget gate ignored"
            exp = lpd.merge(rpd, left_on="k", right_on="k2", how="left")
            assert _norm(got[list(exp.columns)]) == _norm(exp)
        finally:
            sess.conf.set(
                hst.keys.EXEC_JOIN_BROADCAST_MAX_BYTES,
                hst.config.DEFAULTS[hst.keys.EXEC_JOIN_BROADCAST_MAX_BYTES],
            )

    def test_fused_filter_project_over_join(self, broadcast_sides):
        """Filter→Project above a Join streams through the fused post-join
        path and matches the unfused materialized answer."""
        sess, ldf, rdf, lpd, rpd = broadcast_sides
        q = (
            ldf.join(rdf, on=col("k") == col("k2"), how="inner")
            .filter(col("w") > 0.25)
            .select("k", "v", "w")
        )
        got_str = _stream_concat(sess, q.optimized_plan())
        exp = lpd.merge(rpd, left_on="k", right_on="k2", how="inner")
        exp = exp[exp["w"] > 0.25][["k", "v", "w"]]
        assert _norm(got_str[["k", "v", "w"]]) == _norm(exp)
        got_mat = pd.DataFrame(q.collect())
        assert _norm(got_mat[["k", "v", "w"]]) == _norm(exp)

    def test_outer_join_post_filter_applies_after_null_extension(self, broadcast_sides):
        """WHERE over an outer join filters the null-extended result — the
        fused path must not filter pairs before null extension."""
        sess, ldf, rdf, lpd, rpd = broadcast_sides
        q = ldf.join(rdf, on=col("k") == col("k2"), how="left").filter(col("v") > 0.0)
        got = _stream_concat(sess, q.optimized_plan())
        exp = lpd.merge(rpd, left_on="k", right_on="k2", how="left")
        exp = exp[exp["v"] > 0.0]
        assert _norm(got[list(exp.columns)]) == _norm(exp)


class TestQ3Chain:
    def test_three_table_chain_streams_end_to_end(self, tmp_path):
        """q3-shaped: big fact joined through two small dimensions with a
        filter and projection — streamed ≡ materialized ≡ pandas."""
        rng = np.random.default_rng(21)
        n = 3000
        fact = {
            "fk1": rng.integers(0, 40, n).astype(np.int64),
            "fk2": rng.integers(0, 25, n).astype(np.int64),
            "amount": np.round(rng.uniform(0, 100, n), 3),
        }
        d1 = {
            "dk1": np.arange(40, dtype=np.int64),
            "dname": np.array([f"d{i}" for i in range(40)]),
        }
        d2 = {
            "dk2": np.arange(25, dtype=np.int64),
            "region": np.array([f"r{i % 5}" for i in range(25)]),
        }
        fdir = str(tmp_path / "fact")
        os.makedirs(fdir, exist_ok=True)
        for i in range(3):  # multi-file probe side -> multi-chunk stream
            sl = slice(i * n // 3, (i + 1) * n // 3)
            pq.write_table(
                pa.table({k: v[sl] for k, v in fact.items()}),
                os.path.join(fdir, f"part-{i}.parquet"),
            )
        _write(str(tmp_path / "d1"), d1)
        _write(str(tmp_path / "d2"), d2)
        sess = _mk_session(
            tmp_path, **{hst.keys.EXEC_STREAM_CHUNK_BYTES: 8 * 1024}
        )
        f = sess.read_parquet(fdir)
        t1 = sess.read_parquet(str(tmp_path / "d1"))
        t2 = sess.read_parquet(str(tmp_path / "d2"))
        q = (
            f.join(t1, on=col("fk1") == col("dk1"))
            .join(t2, on=col("fk2") == col("dk2"))
            .filter(col("region") == "r2")
            .select("dname", "region", "amount")
        )
        exp = (
            pd.DataFrame(fact)
            .merge(pd.DataFrame(d1), left_on="fk1", right_on="dk1")
            .merge(pd.DataFrame(d2), left_on="fk2", right_on="dk2")
        )
        exp = exp[exp["region"] == "r2"][["dname", "region", "amount"]]
        before = _counter("hs_join_broadcast_total")
        got_str = _stream_concat(sess, q.optimized_plan())
        # both joins of the chain ride the broadcast streaming path
        assert _counter("hs_join_broadcast_total") >= before + 2
        assert _norm(got_str[["dname", "region", "amount"]]) == _norm(exp)
        got_mat = pd.DataFrame(q.collect())
        assert _norm(got_mat[["dname", "region", "amount"]]) == _norm(exp)

    def test_probe_compile_flatness_across_chunk_sizes(self, tmp_path):
        """Sweeping the probe chunk size must not mint per-chunk-shape probe
        executables: √2 shape buckets keep it to ≤3 per stream."""
        from hyperspace_tpu.exec import device as D

        rng = np.random.default_rng(31)
        n = 4000
        fdir = str(tmp_path / "fact")
        os.makedirs(fdir, exist_ok=True)
        for i in range(4):
            sl = slice(i * n // 4, (i + 1) * n // 4)
            pq.write_table(
                pa.table(
                    {
                        "k": rng.integers(0, 30, n).astype(np.int64)[sl],
                        "v": rng.standard_normal(n)[sl],
                    }
                ),
                os.path.join(fdir, f"part-{i}.parquet"),
            )
        _write(
            str(tmp_path / "dim"),
            {"k2": np.arange(30, dtype=np.int64), "w": np.ones(30)},
        )
        sess = _mk_session(tmp_path)
        dim = sess.read_parquet(str(tmp_path / "dim"))

        def run(chunk_bytes):
            sess.conf.set(hst.keys.EXEC_STREAM_CHUNK_BYTES, chunk_bytes)
            q = sess.read_parquet(fdir).join(dim, on=col("k") == col("k2"))
            return _stream_concat(sess, q.optimized_plan())

        baseline = run(16 * 1024)
        probes = lambda: {  # noqa: E731
            key for key in D._COMPILE_SEEN if key[0] == "hash-probe"
        }
        seen0 = probes()
        for cb in (4 * 1024, 24 * 1024, 64 * 1024, 256 * 1024 * 1024):
            got = run(cb)
            assert len(got) == len(baseline)
        new = probes() - seen0
        assert len(new) <= 3, f"probe executables not flat: {sorted(new)}"


# --------------------------------------------------------------------------
# HLO contracts
# --------------------------------------------------------------------------


class TestHloContracts:
    def test_join_programs_verify_with_zero_violations(self, tmp_path):
        from hyperspace_tpu.check import hlo_lint

        rng = np.random.default_rng(41)
        _write(
            str(tmp_path / "l"),
            {"k": rng.integers(0, 20, 1500).astype(np.int64), "v": rng.standard_normal(1500)},
        )
        _write(
            str(tmp_path / "r"),
            {"k2": np.arange(20, dtype=np.int64), "w": rng.standard_normal(20)},
        )
        sess = _mk_session(tmp_path, **{hst.keys.CHECK_HLO_ENABLED: True})
        q = (
            sess.read_parquet(str(tmp_path / "l"))
            .join(sess.read_parquet(str(tmp_path / "r")), on=col("k") == col("k2"))
            .filter(col("w") > 0.0)
            .select("k", "v", "w")
        )
        _stream_concat(sess, q.optimized_plan())
        families = {key.split("/", 1)[0] for key, _sig in hlo_lint._VERIFIED_SEEN}
        assert {"hash-build", "hash-probe", "fused-postjoin"} <= families
        assert hlo_lint.runtime_violations() == []


# --------------------------------------------------------------------------
# bucketed SMJ: pipelined streaming
# --------------------------------------------------------------------------


@pytest.fixture()
def smj_sides(tmp_path):
    """Two indexed sides so the bucketed SMJ applies; key skew leaves some
    buckets empty on one side."""
    rng = np.random.default_rng(51)
    n, m = 3000, 2200
    ldata = {
        "a": (rng.integers(0, 40, n) * 3).astype(np.int64),  # stride -> empty buckets
        "v": np.round(rng.standard_normal(n), 4),
    }
    rdata = {
        "b": (rng.integers(0, 55, m) * 3).astype(np.int64),
        "w": np.round(rng.standard_normal(m), 4),
    }
    _write(str(tmp_path / "l"), ldata)
    _write(str(tmp_path / "r"), rdata)
    sess = _mk_session(
        tmp_path,
        **{
            hst.keys.NUM_BUCKETS: 8,
            hst.keys.EXEC_JOIN_BROADCAST_MAX_BYTES: 0,  # isolate the SMJ path
        },
    )
    hs = hst.Hyperspace(sess)
    ldf = sess.read_parquet(str(tmp_path / "l"))
    rdf = sess.read_parquet(str(tmp_path / "r"))
    hs.create_index(ldf, hst.CoveringIndexConfig("sjL", ["a"], ["v"]))
    hs.create_index(rdf, hst.CoveringIndexConfig("sjR", ["b"], ["w"]))
    sess.enable_hyperspace()
    return sess, ldf, rdf, pd.DataFrame(ldata), pd.DataFrame(rdata)


class TestPipelinedSMJ:
    @pytest.mark.parametrize("how", ["inner", "left", "right", "outer"])
    def test_pipelined_equals_serial_equals_oracle(self, smj_sides, how):
        sess, ldf, rdf, lpd, rpd = smj_sides
        q = ldf.join(rdf, on=col("a") == col("b"), how=how).select("v", "w")
        plan = q.optimized_plan()
        exp = lpd.merge(
            rpd, left_on="a", right_on="b", how="outer" if how == "outer" else how
        )[["v", "w"]]
        pipelined = _stream_concat(sess, plan)
        sess.conf.set(hst.keys.EXEC_JOIN_PIPELINE_ENABLED, False)
        try:
            serial = _stream_concat(sess, plan)
        finally:
            sess.conf.set(hst.keys.EXEC_JOIN_PIPELINE_ENABLED, True)
        assert _norm(pipelined[["v", "w"]]) == _norm(exp)
        assert _norm(serial[["v", "w"]]) == _norm(exp)
        # determinism pin: both orders produce identical output dtypes
        assert list(pipelined.dtypes.items()) == list(serial.dtypes.items())

    def test_dispatch_stream_fold_matches(self, smj_sides):
        """The streaming-threshold path's incremental fold (no full
        list(...) materialization) returns the same batch."""
        sess, ldf, rdf, lpd, rpd = smj_sides
        q = ldf.join(rdf, on=col("a") == col("b"), how="outer").select("v", "w")
        sess.conf.set(hst.keys.EXEC_STREAM_JOIN_MIN_BYTES, 0)  # force streamed dispatch
        try:
            got = pd.DataFrame(q.collect())
        finally:
            sess.conf.set(
                hst.keys.EXEC_STREAM_JOIN_MIN_BYTES,
                hst.config.DEFAULTS[hst.keys.EXEC_STREAM_JOIN_MIN_BYTES],
            )
        exp = lpd.merge(rpd, left_on="a", right_on="b", how="outer")[["v", "w"]]
        assert _norm(got[["v", "w"]]) == _norm(exp)

    def test_midstream_close_releases_bucket_readers(self, smj_sides, monkeypatch):
        """Regression (pipeline cancel-safety): close() after one chunk must
        stop both sides' bucket decodes — queued readers are cancelled, not
        drained."""
        from hyperspace_tpu.exec import device as D

        sess, ldf, rdf, _lpd, _rpd = smj_sides
        calls = []
        orig = D._side_bucket_readers

        def spy(session, side, cols, keys):
            readers = orig(session, side, cols, keys)

            def wrap(b, fn):
                def run():
                    calls.append(b)
                    return fn()

                return run

            return {b: wrap(b, fn) for b, fn in readers.items()}

        monkeypatch.setattr(D, "_side_bucket_readers", spy)
        q = ldf.join(rdf, on=col("a") == col("b")).select("v", "w")
        join_node = L.collect(
            q.optimized_plan(), lambda p: isinstance(p, L.Join)
        )[0]
        gen = D.stream_bucketed_join(sess, join_node)
        next(gen)
        gen.close()
        n_after_close = len(calls)
        time.sleep(0.4)  # any still-running worker would keep decoding
        assert len(calls) == n_after_close, "decodes continued after close()"
        # 8 buckets x 2 sides fully drained would be 16: closing after one
        # chunk must leave the tail un-decoded (1 consumed + lookahead)
        assert n_after_close < 16, f"close() drained the whole stream ({n_after_close})"


class TestDtypeHintFallback:
    def test_dropped_hint_bumps_metric_and_trace(self):
        """An unresolvable output column no longer silently loses its dtype
        hint: the decision is surfaced as a device-fallback metric + trace."""
        from hyperspace_tpu.exec import device as D
        from hyperspace_tpu.obs.metrics import REGISTRY

        class _FakeJoin:
            output_columns = ["ghost"]

        lside = L.FileScan([], "parquet", ["a"])
        rside = L.FileScan([], "parquet", ["b"])

        def fallback_count():
            snap = REGISTRY.snapshot().get("hs_device_fallback_total")
            if not snap:
                return 0.0
            return sum(
                s["value"]
                for s in snap["series"]
                if s["labels"].get("op") == "join"
                and s["labels"].get("reason") == "dtype_hint"
            )

        before = fallback_count()
        hints = D._stream_join_dtype_hints(_FakeJoin(), lside, rside, ["a"], ["b"])
        assert hints == {}
        assert fallback_count() == before + 1


# --------------------------------------------------------------------------
# shared build sides
# --------------------------------------------------------------------------


class TestJoinBuildCache:
    def test_hit_miss_and_weigh(self):
        from hyperspace_tpu.serving.build_cache import JoinBuildCache

        c = JoinBuildCache(max_bytes=1000)
        built = []

        def builder():
            built.append(1)
            return {"x": 1}

        v1 = c.get_or_build("s1", "brandA", builder, lambda v: 100)
        v2 = c.get_or_build("s1", "brandA", builder, lambda v: 100)
        assert v1 is v2 and len(built) == 1
        assert c.stats()["hits"] == 1 and c.stats()["misses"] == 1

    def test_brand_rotation_invalidates(self):
        from hyperspace_tpu.serving.build_cache import JoinBuildCache

        c = JoinBuildCache(max_bytes=1000)
        c.get_or_build("s1", "brandA", lambda: "old", lambda v: 10)
        # new data version observed for the same structure: stale purged
        got = c.get_or_build("s1", "brandB", lambda: "new", lambda v: 10)
        assert got == "new"
        assert c.stats()["invalidations"] == 1
        assert len(c) == 1
        # the old brand can never be served again
        again = c.get_or_build("s1", "brandB", lambda: "newer", lambda v: 10)
        assert again == "new"

    def test_byte_budget_evicts_lru(self):
        from hyperspace_tpu.serving.build_cache import JoinBuildCache

        c = JoinBuildCache(max_bytes=250)
        c.get_or_build("s1", "b", lambda: "v1", lambda v: 100)
        c.get_or_build("s2", "b", lambda: "v2", lambda v: 100)
        c.get_or_build("s3", "b", lambda: "v3", lambda v: 100)  # evicts s1
        assert c.stats()["evictions"] == 1
        assert c.stats()["bytes"] == 200
        rebuilt = []
        c.get_or_build("s1", "b", lambda: rebuilt.append(1) or "v1b", lambda v: 100)
        assert rebuilt, "evicted entry must rebuild"

    def test_oversized_value_served_uncached(self):
        from hyperspace_tpu.serving.build_cache import JoinBuildCache

        c = JoinBuildCache(max_bytes=50)
        v = c.get_or_build("s1", "b", lambda: "big", lambda v: 500)
        assert v == "big" and len(c) == 0


class TestServingSharedBuilds:
    def test_build_cache_hits_under_serving(self, tmp_path):
        """Micro-batched requests joining the same dimension table pay ONE
        hash-table build: the second request hits the shared cache."""
        from hyperspace_tpu.serving import QueryServer

        rng = np.random.default_rng(61)
        _write(
            str(tmp_path / "fact"),
            {
                "k": rng.integers(0, 30, 2000).astype(np.int64),
                "v": rng.standard_normal(2000),
            },
        )
        _write(
            str(tmp_path / "dim"),
            {"k2": np.arange(30, dtype=np.int64), "w": rng.standard_normal(30)},
        )
        sess = _mk_session(tmp_path)
        fact = sess.read_parquet(str(tmp_path / "fact"))
        dim = sess.read_parquet(str(tmp_path / "dim"))
        before = _counter("hs_join_build_cache_hits_total")
        with QueryServer(sess, workers=2, result_cache_enabled=False) as srv:
            q = fact.join(dim, on=col("k") == col("k2")).select("k", "v", "w")
            futs = [srv.submit(q, timeout=60) for _ in range(4)]
            rows = [len(f.result(timeout=60)["k"]) for f in futs]
            assert len(set(rows)) == 1
            stats = srv.join_build_cache.stats()
        assert stats["hits"] >= 1, stats
        assert stats["misses"] >= 1
        assert _counter("hs_join_build_cache_hits_total") > before
        # detached after shutdown
        assert getattr(sess, "join_build_cache", None) is None
