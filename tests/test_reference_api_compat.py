"""Reference-API (camelCase) compatibility surface.

Users of the reference's JVM/PySpark binding keep their call sites
(ref: HS/Hyperspace.scala:27-231, python/hyperspace/hyperspace.py:9-192,
HS/package.scala:36-43, CoveringIndexConfig builder :118-200).
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst


@pytest.fixture()
def data(tmp_path):
    d = tmp_path / "d"
    d.mkdir()
    rng = np.random.default_rng(0)
    pq.write_table(
        pa.table(
            {
                "k": rng.integers(0, 50, 500).astype(np.int64),
                "v": rng.standard_normal(500),
            }
        ),
        d / "p.parquet",
    )
    return str(d)


def test_camel_case_lifecycle(session, data):
    session.conf.set(hst.keys.NUM_BUCKETS, 4)
    hs = hst.Hyperspace(session)
    df = session.read_parquet(data)
    cfg = (
        hst.CoveringIndexConfig.builder()
        .indexName("camelIdx")
        .indexBy("k")
        .include("v")
        .create()
    )
    hs.createIndex(df, cfg)
    session.enableHyperspace()
    assert session.isHyperspaceEnabled()
    q = df.filter(hst.col("k") == 7).select("v")
    on = q.collect()
    session.disableHyperspace()
    off = q.collect()
    session.enableHyperspace()
    assert np.array_equal(np.sort(on["v"]), np.sort(off["v"]))

    rng = np.random.default_rng(1)
    pq.write_table(
        pa.table(
            {
                "k": rng.integers(0, 50, 100).astype(np.int64),
                "v": rng.standard_normal(100),
            }
        ),
        f"{data}/p2.parquet",
    )
    hs.refreshIndex("camelIdx", "full")
    try:
        hs.optimizeIndex("camelIdx")
    except Exception as e:
        assert "No index files" in str(e) or "NoChanges" in type(e).__name__
    assert hs.whyNot(q)
    hs.deleteIndex("camelIdx")
    hs.restoreIndex("camelIdx")
    hs.deleteIndex("camelIdx")
    hs.vacuumIndex("camelIdx")


def test_builder_validation():
    with pytest.raises(ValueError, match="indexName"):
        hst.CoveringIndexConfig.builder().indexBy("k").create()
    b = hst.CoveringIndexConfig.builder().indexName("x")
    with pytest.raises(ValueError, match="already set"):
        b.indexName("y")
