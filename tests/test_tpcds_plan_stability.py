"""TPC-DS plan-stability golden suite.

Mirrors the reference's goldstandard: all 24 TPC-DS tables created up front,
index-eligible query shapes run through the full optimizer, normalized
optimized-plan text compared against approved files
(ref: goldstandard/TPCDSBase.scala:35-563 — table roster :543-553;
PlanStabilitySuite.scala:83-290). Queries are the star-join/filter skeletons
of their TPC-DS namesakes, restricted to the plan algebra the rules accept
(linear filter/project + conjunctive equi-joins, per JoinPlanNodeFilter,
ref: JoinIndexRule.scala:135-155). Regenerate with HS_GENERATE_GOLDEN=1.
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu import col

APPROVED_DIR = os.path.join(os.path.dirname(__file__), "approved_plans", "tpcds")
GENERATE = os.environ.get("HS_GENERATE_GOLDEN", "") == "1"

I, F, S, D = np.int64, np.float64, "str", "datetime64[D]"

# All 24 TPC-DS tables (ref: TPCDSBase.scala:543-553), with the key columns
# plus the measures/attributes the query shapes below reference.
TPCDS_SCHEMAS = {
    "call_center": {"cc_call_center_sk": I, "cc_county": S},
    "catalog_page": {"cp_catalog_page_sk": I, "cp_type": S},
    "catalog_returns": {"cr_returned_date_sk": I, "cr_item_sk": I, "cr_order_number": I, "cr_return_amount": F},
    "catalog_sales": {
        "cs_sold_date_sk": I, "cs_item_sk": I, "cs_bill_customer_sk": I,
        "cs_order_number": I, "cs_quantity": I, "cs_ext_sales_price": F, "cs_net_profit": F,
    },
    "customer": {
        "c_customer_sk": I, "c_current_addr_sk": I, "c_current_cdemo_sk": I,
        "c_birth_year": I, "c_first_name": S, "c_last_name": S,
    },
    "customer_address": {"ca_address_sk": I, "ca_state": S, "ca_gmt_offset": F},
    "customer_demographics": {"cd_demo_sk": I, "cd_gender": S, "cd_education_status": S},
    "date_dim": {"d_date_sk": I, "d_year": I, "d_moy": I, "d_qoy": I, "d_date": D},
    "household_demographics": {"hd_demo_sk": I, "hd_income_band_sk": I, "hd_dep_count": I},
    "income_band": {"ib_income_band_sk": I, "ib_lower_bound": I, "ib_upper_bound": I},
    "inventory": {"inv_date_sk": I, "inv_item_sk": I, "inv_warehouse_sk": I, "inv_quantity_on_hand": I},
    "item": {
        "i_item_sk": I, "i_brand_id": I, "i_category_id": I, "i_manufact_id": I,
        "i_category": S, "i_current_price": F,
    },
    "promotion": {"p_promo_sk": I, "p_channel_email": S},
    "reason": {"r_reason_sk": I, "r_reason_desc": S},
    "ship_mode": {"sm_ship_mode_sk": I, "sm_type": S},
    "store": {"s_store_sk": I, "s_state": S, "s_number_employees": I},
    "store_returns": {"sr_returned_date_sk": I, "sr_item_sk": I, "sr_ticket_number": I, "sr_return_amt": F},
    "store_sales": {
        "ss_sold_date_sk": I, "ss_item_sk": I, "ss_customer_sk": I, "ss_store_sk": I,
        "ss_ticket_number": I, "ss_quantity": I, "ss_sales_price": F, "ss_ext_sales_price": F, "ss_net_profit": F,
    },
    "time_dim": {"t_time_sk": I, "t_hour": I},
    "warehouse": {"w_warehouse_sk": I, "w_state": S},
    "web_page": {"wp_web_page_sk": I, "wp_char_count": I},
    "web_returns": {"wr_returned_date_sk": I, "wr_item_sk": I, "wr_order_number": I, "wr_return_amt": F},
    "web_sales": {
        "ws_sold_date_sk": I, "ws_item_sk": I, "ws_bill_customer_sk": I,
        "ws_order_number": I, "ws_quantity": I, "ws_ext_sales_price": F, "ws_net_profit": F,
    },
    "web_site": {"web_site_sk": I, "web_state": S},
}


def _write_table(root, name, schema, n=64):
    import zlib

    rng = np.random.default_rng(zlib.crc32(name.encode()))
    cols = {}
    for cname, dt in schema.items():
        if dt == D:
            cols[cname] = np.datetime64("2000-01-01") + rng.integers(0, 1500, n).astype("timedelta64[D]")
        elif dt == S:
            cols[cname] = np.array([f"{cname[:2]}_{v}" for v in rng.integers(0, 12, n)])
        elif dt is F:
            cols[cname] = np.round(rng.uniform(0, 1000, n), 4)
        else:
            cols[cname] = rng.integers(0, 100, n).astype(np.int64)
    d = os.path.join(root, name)
    os.makedirs(d)
    pq.write_table(pa.table(cols), os.path.join(d, "part-00000.parquet"))
    return d


INDEXES = [
    # fact-table FK indexes (the JoinIndexRule pairs) + filter indexes
    ("store_sales", "ss_item", ["ss_item_sk"], ["ss_ext_sales_price", "ss_sold_date_sk"]),
    ("store_sales", "ss_date", ["ss_sold_date_sk"], ["ss_item_sk", "ss_ext_sales_price", "ss_quantity"]),
    ("store_sales", "ss_customer", ["ss_customer_sk"], ["ss_net_profit"]),
    ("store_sales", "ss_store", ["ss_store_sk"], ["ss_sales_price"]),
    ("catalog_sales", "cs_date", ["cs_sold_date_sk"], ["cs_item_sk", "cs_ext_sales_price"]),
    ("catalog_sales", "cs_item", ["cs_item_sk"], ["cs_net_profit"]),
    ("web_sales", "ws_date", ["ws_sold_date_sk"], ["ws_item_sk", "ws_ext_sales_price"]),
    ("web_sales", "ws_item", ["ws_item_sk"], ["ws_net_profit"]),
    ("inventory", "inv_item", ["inv_item_sk"], ["inv_quantity_on_hand", "inv_warehouse_sk"]),
    ("inventory", "inv_wh", ["inv_warehouse_sk"], ["inv_quantity_on_hand"]),
    ("store_returns", "sr_item", ["sr_item_sk"], ["sr_return_amt"]),
    ("item", "i_sk", ["i_item_sk"], ["i_brand_id", "i_category", "i_current_price"]),
    ("item", "i_category_idx", ["i_category"], ["i_item_sk"]),
    ("date_dim", "d_sk", ["d_date_sk"], ["d_year", "d_moy"]),
    ("date_dim", "d_year_idx", ["d_year"], ["d_date_sk"]),
    ("customer", "c_sk", ["c_customer_sk"], ["c_current_addr_sk", "c_birth_year"]),
    ("customer_address", "ca_sk", ["ca_address_sk"], ["ca_state"]),
    ("store", "s_sk", ["s_store_sk"], ["s_state"]),
    ("warehouse", "w_sk", ["w_warehouse_sk"], ["w_state"]),
]


@pytest.fixture(scope="module")
def tpcds(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("tpcds"))
    sysp = os.path.join(root, "_indexes")
    os.makedirs(sysp)
    sess = hst.Session(conf={hst.keys.SYSTEM_PATH: sysp, hst.keys.NUM_BUCKETS: 4})
    hst.set_session(sess)
    hs = hst.Hyperspace(sess)
    dfs = {}
    for name, schema in TPCDS_SCHEMAS.items():
        d = _write_table(root, name, schema)
        dfs[name] = sess.read_parquet(d)
    for table, idx_name, indexed, included in INDEXES:
        hs.create_index(dfs[table], hst.CoveringIndexConfig(idx_name, indexed, included))
    sess.enable_hyperspace()
    yield sess, hs, dfs, root
    hst.set_session(None)


def _queries(dfs):
    ss, cs, ws = dfs["store_sales"], dfs["catalog_sales"], dfs["web_sales"]
    d, i, c = dfs["date_dim"], dfs["item"], dfs["customer"]
    inv, sr = dfs["inventory"], dfs["store_returns"]
    ca, s, w = dfs["customer_address"], dfs["store"], dfs["warehouse"]
    return {
        # q3 skeleton: store_sales x date_dim x item, month filter
        "q03": ss.join(d, on=col("ss_sold_date_sk") == col("d_date_sk"))
        .join(i, on=col("ss_item_sk") == col("i_item_sk"))
        .select("d_year", "i_brand_id", "ss_ext_sales_price"),
        # q42 skeleton: date x store_sales x item with year filter
        "q42": d.filter(col("d_year") == 62)
        .join(ss, on=col("d_date_sk") == col("ss_sold_date_sk"))
        .join(i, on=col("ss_item_sk") == col("i_item_sk"))
        .select("i_category", "ss_ext_sales_price"),
        # q52 skeleton: same star, brand-level projection
        "q52": d.join(ss, on=col("d_date_sk") == col("ss_sold_date_sk"))
        .join(i, on=col("ss_item_sk") == col("i_item_sk"))
        .select("d_year", "i_brand_id", "ss_ext_sales_price"),
        # q55 skeleton: item filter + star
        "q55": i.filter(col("i_manufact_id") > 50)
        .join(ss, on=col("i_item_sk") == col("ss_item_sk"))
        .select("i_brand_id", "ss_ext_sales_price"),
        # q7-like: store_sales with customer
        "q07": ss.join(c, on=col("ss_customer_sk") == col("c_customer_sk"))
        .select("ss_net_profit", "c_birth_year"),
        # q19-like: customer -> address join
        "q19": c.join(ca, on=col("c_current_addr_sk") == col("ca_address_sk"))
        .select("c_birth_year", "ca_state"),
        # q25-like: sales joined with returns on item
        "q25": ss.join(sr, on=col("ss_item_sk") == col("sr_item_sk"))
        .select("ss_net_profit", "sr_return_amt"),
        # q82-like: inventory x item with price filter
        "q82": i.filter(col("i_current_price") >= 500.0)
        .join(inv, on=col("i_item_sk") == col("inv_item_sk"))
        .select("i_current_price", "inv_quantity_on_hand"),
        # q96-like: pure selective filter on a fact table
        "q96": ss.filter(col("ss_sold_date_sk") == 42).select("ss_quantity", "ss_ext_sales_price"),
        # catalog channel star
        "q15": cs.join(d, on=col("cs_sold_date_sk") == col("d_date_sk"))
        .select("cs_ext_sales_price", "d_year"),
        "q20": cs.join(i, on=col("cs_item_sk") == col("i_item_sk"))
        .select("cs_net_profit", "i_category"),
        # web channel star
        "q12": ws.join(d, on=col("ws_sold_date_sk") == col("d_date_sk"))
        .select("ws_ext_sales_price", "d_year"),
        "q60": ws.join(i, on=col("ws_item_sk") == col("i_item_sk"))
        .select("ws_net_profit", "i_brand_id"),
        # inventory x warehouse (both indexed on their join keys)
        "q22": inv.join(w, on=col("inv_warehouse_sk") == col("w_warehouse_sk"))
        .select("inv_quantity_on_hand", "w_state"),
        # dimension-only filters
        "q41": i.filter(col("i_category") == "i__3").select("i_item_sk", "i_current_price"),
        "q84": d.filter((col("d_year") >= 30) & (col("d_year") < 60)).select("d_date_sk", "d_moy"),
        # four-way chain
        "q29": ss.join(d, on=col("ss_sold_date_sk") == col("d_date_sk"))
        .join(i, on=col("ss_item_sk") == col("i_item_sk"))
        .join(c, on=col("ss_customer_sk") == col("c_customer_sk"))
        .select("d_year", "i_brand_id", "c_birth_year", "ss_ext_sales_price"),
        # store dimension join
        "q43": ss.join(s, on=col("ss_store_sk") == col("s_store_sk"))
        .select("ss_sales_price", "s_state"),
        # unindexed path stays unrewritten
        "q90": dfs["web_page"].filter(col("wp_char_count") > 50).select("wp_web_page_sk"),
        "q93": sr.join(dfs["reason"], on=col("sr_item_sk") == col("r_reason_sk"))
        .select("sr_return_amt", "r_reason_desc"),
    }


def _normalize(text: str, root: str) -> str:
    return text.replace(root, "<TPCDS>")


QUERY_NAMES = [
    "q03", "q07", "q12", "q15", "q19", "q20", "q22", "q25", "q29", "q41",
    "q42", "q43", "q52", "q55", "q60", "q82", "q84", "q90", "q93", "q96",
]


@pytest.mark.parametrize("qname", QUERY_NAMES)
def test_plan_stability(tpcds, qname):
    sess, hs, dfs, root = tpcds
    q = _queries(dfs)[qname]
    plan_text = _normalize(q.optimized_plan().pretty(), root)
    path = os.path.join(APPROVED_DIR, f"{qname}.txt")
    if GENERATE:
        os.makedirs(APPROVED_DIR, exist_ok=True)
        with open(path, "w") as f:
            f.write(plan_text)
        return
    with open(path) as f:
        expected = f.read()
    assert plan_text == expected, (
        f"plan for {qname} changed; review and regen with HS_GENERATE_GOLDEN=1\n{plan_text}"
    )


def test_rewrites_fire_where_expected(tpcds):
    """The star joins over indexed fact/dimension keys must use IndexScans;
    the deliberately-unindexed shapes must not."""
    from hyperspace_tpu.plan import logical as L

    sess, hs, dfs, root = tpcds
    queries = _queries(dfs)

    def index_scans(q):
        return [
            p
            for p in L.collect(q.optimized_plan(), lambda p: True)
            if isinstance(p, L.IndexScan)
        ]

    for qname in ("q03", "q42", "q52", "q12", "q22", "q96"):
        assert index_scans(queries[qname]), qname
    for qname in ("q90",):
        assert not index_scans(queries[qname]), qname


def test_all_queries_execute(tpcds):
    """checkAnswer side: whole row tuples equal with indexes on vs off."""
    sess, hs, dfs, root = tpcds
    for name, q in _queries(dfs).items():
        sess.disable_hyperspace()
        base = q.collect()
        sess.enable_hyperspace()
        got = q.collect()
        assert sorted(base.keys()) == sorted(got.keys()), name
        cols = sorted(base.keys())
        base_rows = sorted(zip(*[base[k].tolist() for k in cols]))
        got_rows = sorted(zip(*[got[k].tolist() for k in cols]))
        assert base_rows == got_rows, f"{name}: row sets differ"
