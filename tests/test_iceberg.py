"""Iceberg source provider tests
(ref: src/test/scala/.../IcebergIntegrationTest.scala — index on an Iceberg
table, snapshot-based signatures, hybrid scan over a new snapshot).

Also covers the framework's own Avro container codec round-trip, since
Iceberg manifests depend on it.
"""

import numpy as np
import pyarrow as pa
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.plan import logical as L
from hyperspace_tpu.plan.expr import col
from hyperspace_tpu.sources.iceberg import IcebergRelation, write_iceberg_table
from hyperspace_tpu.utils import avro


def make_table(seed: int, n: int = 500) -> pa.Table:
    rng = np.random.default_rng(seed)
    return pa.table(
        {
            "k": rng.integers(0, 100, n).astype(np.int64),
            "v": rng.standard_normal(n),
        }
    )


@pytest.fixture()
def iceberg_root(tmp_path):
    root = str(tmp_path / "iceberg_tbl")
    write_iceberg_table(make_table(1), root)
    return root


@pytest.fixture()
def hs(session):
    return hst.Hyperspace(session)


class TestAvroCodec:
    def test_round_trip_all_types(self, tmp_path):
        schema = {
            "type": "record",
            "name": "t",
            "fields": [
                {"name": "s", "type": "string"},
                {"name": "i", "type": "int"},
                {"name": "l", "type": "long"},
                {"name": "d", "type": "double"},
                {"name": "b", "type": "boolean"},
                {"name": "by", "type": "bytes"},
                {"name": "opt", "type": ["null", "long"]},
                {"name": "arr", "type": {"type": "array", "items": "long"}},
                {"name": "m", "type": {"type": "map", "values": "string"}},
                {
                    "name": "nested",
                    "type": {
                        "type": "record",
                        "name": "inner",
                        "fields": [{"name": "x", "type": "long"}],
                    },
                },
            ],
        }
        records = [
            {
                "s": "hello",
                "i": -42,
                "l": 1 << 40,
                "d": 3.5,
                "b": True,
                "by": b"\x00\x01",
                "opt": None,
                "arr": [1, 2, 3],
                "m": {"a": "x"},
                "nested": {"x": 7},
            },
            {
                "s": "",
                "i": 0,
                "l": -(1 << 40),
                "d": -0.25,
                "b": False,
                "by": b"",
                "opt": 5,
                "arr": [],
                "m": {},
                "nested": {"x": -1},
            },
        ]
        path = str(tmp_path / "t.avro")
        avro.write_container(path, schema, records)
        rschema, rrecords = avro.read_container(path)
        assert rschema == schema
        assert rrecords == records

    @staticmethod
    def _write_snappy_container(path, schema, block_count, framed):
        """Hand-frame a snappy-codec Avro container around one pre-framed
        block (raw snappy + big-endian CRC32)."""
        import io as _io
        import json as _json

        body = _io.BytesIO()
        body.write(avro.MAGIC)
        body.write(b"\x04")  # metadata map block count 2 (zigzag)
        for k, v in {
            "avro.schema": _json.dumps(schema).encode(),
            "avro.codec": b"snappy",
        }.items():
            kb = k.encode()
            avro._write_long(body, len(kb)); body.write(kb)
            avro._write_long(body, len(v)); body.write(v)
        body.write(b"\x00")
        sync = b"S" * 16
        body.write(sync)
        avro._write_long(body, block_count)
        avro._write_long(body, len(framed))
        body.write(framed)
        body.write(sync)
        with open(path, "wb") as f:
            f.write(body.getvalue())

    def test_snappy_codec_blocks(self, tmp_path):
        """Snappy-codec Avro containers (raw snappy block + big-endian CRC32
        framing per the Avro spec) decode — both through the native snappy
        decoder and the pure-Python fallback."""
        import io as _io
        import zlib

        import pyarrow as pa

        schema = {
            "type": "record",
            "name": "t",
            "fields": [{"name": "s", "type": "string"}, {"name": "l", "type": "long"}],
        }
        records = [{"s": f"row_{i % 7}", "l": i * 1000} for i in range(500)]
        payload = _io.BytesIO()
        names = {}
        for r in records:
            avro._encode(schema, r, payload, names)
        plain = payload.getvalue()
        comp = pa.compress(plain, codec="snappy", asbytes=True)
        framed = comp + (zlib.crc32(plain) & 0xFFFFFFFF).to_bytes(4, "big")
        path = str(tmp_path / "snappy.avro")
        self._write_snappy_container(path, schema, len(records), framed)

        rschema, rrecords = avro.read_container(path)
        assert rschema == schema
        assert rrecords == records

        # pure-Python fallback path agrees
        from hyperspace_tpu.utils.avro import _snappy_decompress

        import hyperspace_tpu.native as native_mod

        real = native_mod.snappy_decompress
        try:
            def boom(blob):
                raise native_mod.NativeUnsupported("forced")

            native_mod.snappy_decompress = boom
            assert _snappy_decompress(comp) == plain
        finally:
            native_mod.snappy_decompress = real

    def test_snappy_crc_mismatch_raises(self, tmp_path):
        import io as _io
        import zlib  # noqa: F401

        import pyarrow as pa

        schema = {"type": "record", "name": "t", "fields": [{"name": "l", "type": "long"}]}
        _b = _io.BytesIO()
        avro._write_long(_b, 42)
        plain = _b.getvalue()
        comp = pa.compress(plain, codec="snappy", asbytes=True)
        path = str(tmp_path / "bad.avro")
        self._write_snappy_container(path, schema, 1, comp + b"\x00\x00\x00\x00")
        with pytest.raises(ValueError, match="CRC"):
            avro.read_container(path)

    def test_zigzag_varint_edge_values(self, tmp_path):
        schema = {"type": "record", "name": "t", "fields": [{"name": "x", "type": "long"}]}
        values = [0, -1, 1, 63, 64, -64, -65, (1 << 62), -(1 << 62)]
        path = str(tmp_path / "z.avro")
        avro.write_container(path, schema, [{"x": v} for v in values])
        _, records = avro.read_container(path)
        assert [r["x"] for r in records] == values


class TestIcebergRelation:
    def test_read_and_snapshots(self, session, iceberg_root):
        df = session.read_iceberg(iceberg_root)
        out = df.collect()
        assert len(out["k"]) == 500
        rel = df.plan.relation
        assert isinstance(rel, IcebergRelation)
        assert rel.has_parquet_as_source_format()
        sig1 = rel.signature()

        write_iceberg_table(make_table(2), iceberg_root)
        rel2 = session.read_iceberg(iceberg_root).plan.relation
        assert rel2.signature() != sig1  # snapshot id changed
        assert len(session.read_iceberg(iceberg_root).collect()["k"]) == 1000

    def test_snapshot_time_travel(self, session, iceberg_root):
        first_rel = session.read_iceberg(iceberg_root).plan.relation
        first_snap = first_rel.snapshot_id
        write_iceberg_table(make_table(2), iceberg_root)
        old_df = session.read_iceberg(iceberg_root, snapshot_id=first_snap)
        assert len(old_df.collect()["k"]) == 500
        assert old_df.plan.relation.signature() == first_rel.signature()

    def test_index_on_iceberg_and_query(self, session, hs, iceberg_root):
        df = session.read_iceberg(iceberg_root)
        hs.create_index(df, hst.CoveringIndexConfig("iceIdx", ["k"], ["v"]))
        q = df.filter(col("k") == 7).select("v")
        baseline = q.collect()
        session.enable_hyperspace()
        plan = q.optimized_plan()
        assert any(isinstance(p, L.IndexScan) for p in L.collect(plan, lambda p: True)), plan.pretty()
        out = q.collect()
        np.testing.assert_allclose(np.sort(out["v"]), np.sort(baseline["v"]))

    def test_new_snapshot_invalidates_index(self, session, hs, iceberg_root):
        df = session.read_iceberg(iceberg_root)
        hs.create_index(df, hst.CoveringIndexConfig("iceStale", ["k"], ["v"]))
        write_iceberg_table(make_table(2), iceberg_root)
        session.enable_hyperspace()
        df2 = session.read_iceberg(iceberg_root)
        plan = df2.filter(col("k") == 7).select("v").optimized_plan()
        assert not any(isinstance(p, L.IndexScan) for p in L.collect(plan, lambda p: True))

    def test_hybrid_scan_over_new_snapshot(self, session, hs, iceberg_root):
        df = session.read_iceberg(iceberg_root)
        hs.create_index(df, hst.CoveringIndexConfig("iceHybrid", ["k"], ["v"]))
        write_iceberg_table(make_table(2), iceberg_root)
        session.conf.set(hst.keys.HYBRID_SCAN_ENABLED, True)
        session.conf.set(hst.keys.HYBRID_SCAN_MAX_APPENDED_RATIO, 0.9)
        df2 = session.read_iceberg(iceberg_root)
        q = df2.filter(col("k") == 7).select("v")
        baseline = q.collect()
        session.enable_hyperspace()
        plan = q.optimized_plan()
        assert any(isinstance(p, L.BucketUnion) for p in L.collect(plan, lambda p: True)), plan.pretty()
        out = q.collect()
        np.testing.assert_allclose(np.sort(out["v"]), np.sort(baseline["v"]))

    def test_refresh_incremental_on_iceberg(self, session, hs, iceberg_root):
        df = session.read_iceberg(iceberg_root)
        hs.create_index(df, hst.CoveringIndexConfig("iceRef", ["k"], ["v"]))
        write_iceberg_table(make_table(3), iceberg_root)
        entry = hs.refresh_index("iceRef", "incremental")
        assert entry.state == "ACTIVE"
        session.enable_hyperspace()
        df2 = session.read_iceberg(iceberg_root)
        q = df2.filter(col("k") == 7).select("v")
        plan = q.optimized_plan()
        assert any(isinstance(p, L.IndexScan) for p in L.collect(plan, lambda p: True)), plan.pretty()
        session.disable_hyperspace()
        baseline = q.collect()
        session.enable_hyperspace()
        np.testing.assert_allclose(np.sort(q.collect()["v"]), np.sort(baseline["v"]))
