"""Importing the library must not mutate global JAX state.

The reference is a guest inside SparkSession and never flips engine-wide
flags behind the host's back; the same courtesy applies here — x64 is
enabled by ``Session()`` / lazily at first device use (utils/x64.py), not
at import (ref: HS/package.scala:29-69 installs rules only on an explicit
``spark.enableHyperspace()`` call).
"""

import subprocess
import sys


def test_import_does_not_enable_x64():
    code = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import hyperspace_tpu\n"
        "import hyperspace_tpu.exec.device\n"
        "import hyperspace_tpu.ops.sort\n"
        "import hyperspace_tpu.ops.bucketize\n"
        "import hyperspace_tpu.ops.kernels\n"
        "assert jax.config.jax_enable_x64 is False, 'import flipped x64'\n"
        "from hyperspace_tpu.session import Session\n"
        "Session()\n"
        "assert jax.config.jax_enable_x64 is True, 'Session() must enable x64'\n"
        "print('ok')\n"
    )
    res = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, timeout=180
    )
    assert res.returncode == 0, res.stderr[-2000:]
    assert "ok" in res.stdout


def test_ops_entry_points_self_enable_x64():
    # direct library users who skip Session still get working int64 sorts
    code = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import numpy as np\n"
        "from hyperspace_tpu.ops.sort import lex_argsort\n"
        "assert jax.config.jax_enable_x64 is False\n"
        "perm = lex_argsort([np.array([3, 1, 2], dtype=np.int64)])\n"
        "assert list(np.asarray(perm)) == [1, 2, 0]\n"
        "assert jax.config.jax_enable_x64 is True\n"
        "print('ok')\n"
    )
    res = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, timeout=180
    )
    assert res.returncode == 0, res.stderr[-2000:]
    assert "ok" in res.stdout
