"""Hive-partitioned dataset support.

The reference indexes partitioned data through Spark's partition-aware file
index and has dedicated suites for it (E2EHyperspaceRulesTest partitioned
cases, HybridScanForPartitionedDataTest — SURVEY.md §4); here partition
columns come from .../col=value/... path segments (sources/partitions.py).
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.exec import batch as B
from hyperspace_tpu.plan import logical as L
from hyperspace_tpu.sources import partitions


def sort_batch(b):
    order = np.lexsort([v.astype(str) if v.dtype == object else v for v in reversed(list(b.values()))])
    return {k: v[order] for k, v in b.items()}


def assert_same(a, b):
    assert sorted(a.keys()) == sorted(b.keys())
    assert B.num_rows(a) == B.num_rows(b)
    a, b = sort_batch(a), sort_batch(b)
    for k in a:
        np.testing.assert_array_equal(a[k], b[k], err_msg=k)


@pytest.fixture()
def hs(session):
    return hst.Hyperspace(session)


def write_partitioned(root, depts=(3, 7, 11), rows=400, seed=0):
    rng = np.random.default_rng(seed)
    for d in depts:
        p = os.path.join(root, f"dept={d}")
        os.makedirs(p, exist_ok=True)
        pq.write_table(
            pa.table(
                {
                    "id": rng.integers(0, 10_000, rows).astype(np.int64),
                    "value": rng.standard_normal(rows),
                }
            ),
            os.path.join(p, "part-0.parquet"),
        )
    return str(root)


class TestDiscovery:
    def test_types_and_values(self, tmp_path):
        root = tmp_path / "t"
        for seg, name in [("a=1", "x.parquet"), ("a=2", "y.parquet")]:
            d = root / seg
            d.mkdir(parents=True)
            pq.write_table(pa.table({"v": np.arange(2, dtype=np.int64)}), d / name)
        files = sorted(str(p) for p in root.rglob("*.parquet"))
        cols, raw = partitions.discover(files, [str(root)])
        assert cols == ["a"]
        dt = partitions.infer_dtypes(cols, raw)
        assert dt["a"] == np.dtype(np.int64)

    def test_mixed_layout_is_unpartitioned(self, tmp_path):
        root = tmp_path / "t"
        (root / "a=1").mkdir(parents=True)
        pq.write_table(pa.table({"v": np.arange(2, dtype=np.int64)}), root / "a=1" / "x.parquet")
        pq.write_table(pa.table({"v": np.arange(2, dtype=np.int64)}), root / "flat.parquet")
        files = sorted(str(p) for p in root.rglob("*.parquet"))
        cols, _ = partitions.discover(files, [str(root)])
        assert cols == []

    def test_hive_null_promotes_int_to_float(self, tmp_path):
        root = tmp_path / "t"
        for seg in ("a=1", f"a={partitions.HIVE_NULL}"):
            d = root / seg
            d.mkdir(parents=True)
            pq.write_table(pa.table({"v": np.arange(2, dtype=np.int64)}), d / "x.parquet")
        files = sorted(str(p) for p in root.rglob("*.parquet"))
        cols, raw = partitions.discover(files, [str(root)])
        dt = partitions.infer_dtypes(cols, raw)
        assert dt["a"] == np.dtype(np.float64)

    def test_url_decoding(self, tmp_path):
        root = tmp_path / "t"
        d = root / "city=new%20york"
        d.mkdir(parents=True)
        pq.write_table(pa.table({"v": np.arange(1, dtype=np.int64)}), d / "x.parquet")
        files = [str(next(root.rglob("*.parquet")))]
        cols, raw = partitions.discover(files, [str(root)])
        assert cols == ["city"]
        assert list(raw.values())[0]["city"] == "new york"


class TestPartitionedQueries:
    def test_scan_exposes_partition_column(self, session, tmp_path):
        root = write_partitioned(tmp_path / "d")
        df = session.read_parquet(root)
        out = df.collect()
        assert "dept" in out
        assert set(np.unique(out["dept"])) == {3, 7, 11}

    def test_partition_pruning_reads_fewer_files(self, session, tmp_path, monkeypatch):
        root = write_partitioned(tmp_path / "d")
        df = session.read_parquet(root)
        import hyperspace_tpu.exec.executor as E

        seen = []
        real = E._read_files

        def spy(files, *a, **k):
            seen.append(list(files))
            return real(files, *a, **k)

        monkeypatch.setattr(E, "_read_files", spy)
        out = df.filter(hst.col("dept") == 7).collect()
        assert all(v == 7 for v in out["dept"])
        assert len(seen[-1]) == 1  # one partition dir -> one file read

    def test_filter_index_over_partitioned_data(self, session, hs, tmp_path):
        root = write_partitioned(tmp_path / "d")
        session.conf.set(hst.keys.NUM_BUCKETS, 8)
        df = session.read_parquet(root)
        hs.create_index(df, hst.CoveringIndexConfig("pIdx", ["id"], ["value", "dept"]))
        session.enable_hyperspace()
        q = df.filter(hst.col("id") < 500).select("id", "value", "dept")
        plan = q.optimized_plan()
        scans = [p for p in L.collect(plan, lambda p: True) if isinstance(p, L.IndexScan)]
        assert scans, plan.pretty()
        on = q.collect()
        session.disable_hyperspace()
        off = q.collect()
        session.enable_hyperspace()
        assert_same(on, off)

    def test_index_on_partition_column(self, session, hs, tmp_path):
        """The partition column itself can be an indexed column."""
        root = write_partitioned(tmp_path / "d")
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        df = session.read_parquet(root)
        hs.create_index(df, hst.CoveringIndexConfig("pdeptIdx", ["dept"], ["value"]))
        session.enable_hyperspace()
        q = df.filter(hst.col("dept") == 7).select("value")
        plan = q.optimized_plan()
        scans = [p for p in L.collect(plan, lambda p: True) if isinstance(p, L.IndexScan)]
        assert scans, plan.pretty()
        on = q.collect()
        session.disable_hyperspace()
        off = q.collect()
        session.enable_hyperspace()
        assert_same(on, off)
        assert B.num_rows(on) == 400

    def test_lineage_build_over_partitioned_data(self, session, hs, tmp_path):
        root = write_partitioned(tmp_path / "d")
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        session.conf.set(hst.keys.LINEAGE_ENABLED, True)
        df = session.read_parquet(root)
        hs.create_index(df, hst.CoveringIndexConfig("plinIdx", ["id"], ["dept"]))
        entry = session.index_manager.get_index("plinIdx")
        assert entry is not None

    def test_hybrid_scan_append_new_partition(self, session, hs, tmp_path):
        root = write_partitioned(tmp_path / "d")
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        df = session.read_parquet(root)
        hs.create_index(df, hst.CoveringIndexConfig("phyIdx", ["id"], ["value", "dept"]))
        # new partition appears after indexing
        write_partitioned(tmp_path / "d", depts=(13,), rows=100, seed=5)
        session.conf.set(hst.keys.HYBRID_SCAN_ENABLED, True)
        session.enable_hyperspace()
        df2 = session.read_parquet(root)
        q = df2.filter(hst.col("id") >= 0).select("id", "value", "dept")
        plan = q.optimized_plan()
        unions = [p for p in L.collect(plan, lambda p: True) if isinstance(p, L.BucketUnion)]
        assert unions, plan.pretty()
        on = q.collect()
        session.disable_hyperspace()
        off = q.collect()
        session.enable_hyperspace()
        assert_same(on, off)
        assert set(np.unique(on["dept"])) == {3, 7, 11, 13}

    def test_filescan_with_only_partition_columns(self, session, tmp_path):
        """A FileScan whose requested columns are all partition columns must
        still produce one row per file row (the file is not decoded, only
        counted)."""
        from hyperspace_tpu.exec.executor import Executor

        root = write_partitioned(tmp_path / "d", depts=(7,), rows=5)
        df = session.read_parquet(root)
        rel = df.plan.relation
        files = [fi.name for fi in rel.all_file_infos()]
        scan = L.FileScan(
            files,
            "parquet",
            ["dept"],
            partition_values={f: rel.partition_values_for(f) for f in files},
            partition_dtypes=rel.partition_dtypes,
        )
        out = Executor(session).execute(scan, required_columns=["dept"])
        assert len(out["dept"]) == 5
        assert all(v == 7 for v in out["dept"])

    def test_join_over_partitioned_tables(self, session, hs, tmp_path):
        lroot = write_partitioned(tmp_path / "l", depts=(1, 2), rows=300, seed=1)
        rroot = tmp_path / "r"
        rroot.mkdir()
        rng = np.random.default_rng(2)
        pq.write_table(
            pa.table(
                {
                    "id": rng.integers(0, 10_000, 500).astype(np.int64),
                    "w": rng.standard_normal(500),
                }
            ),
            rroot / "p.parquet",
        )
        session.conf.set(hst.keys.NUM_BUCKETS, 8)
        ldf = session.read_parquet(lroot)
        rdf = session.read_parquet(str(rroot))
        hs.create_index(ldf, hst.CoveringIndexConfig("pjL", ["id"], ["value", "dept"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("pjR", ["id"], ["w"]))
        session.enable_hyperspace()
        q = ldf.join(rdf, on="id").select("id", "dept", "value", "w")
        plan = q.optimized_plan()
        scans = [p for p in L.collect(plan, lambda p: True) if isinstance(p, L.IndexScan)]
        assert len(scans) == 2, plan.pretty()
        on = q.collect()
        session.disable_hyperspace()
        off = q.collect()
        session.enable_hyperspace()
        assert_same(on, off)
