"""Observability under the serving runtime: a TPC-H query served through a
``QueryServer`` with 8 concurrent submitter threads must yield one disjoint
span tree per request (the cross-request isolation the process-global
``exec/trace.py`` recorder cannot give), the ``ServingStatsEvent`` snapshot
must agree field-for-field with the metrics registry (they read the same
store), and served profiles must export valid Chrome trace-event JSON."""

import json
import os
import threading

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.obs import metrics as obs_metrics
from hyperspace_tpu.serving import QueryServer
from test_obs import _validate_chrome
from tpch_queries import TPCH_QUERIES

pytestmark = pytest.mark.obs

N_THREADS = 8
REQS_PER_THREAD = 3


@pytest.fixture()
def lineitem_sess(tmp_path):
    """A lineitem-shaped table sized for q6 plus a shipdate covering index —
    the lifecycle exercised is the real one (optimize applies the index,
    execute decodes index buckets)."""
    n = 4000
    rng = np.random.default_rng(6)
    cols = {
        "l_orderkey": rng.integers(0, 1000, n).astype(np.int64),
        "l_quantity": rng.integers(1, 60, n).astype(np.int64),
        "l_extendedprice": np.round(rng.uniform(0, 2000, n), 2),
        "l_discount": np.round(rng.integers(0, 11, n) / 100.0, 2),
        "l_shipdate": np.datetime64("1992-01-01")
        + rng.integers(0, 2500, n).astype("timedelta64[D]"),
    }
    d = tmp_path / "lineitem"
    d.mkdir()
    pq.write_table(pa.table(cols), str(d / "part-00000.parquet"))
    sysp = tmp_path / "_indexes"
    sysp.mkdir()
    sess = hst.Session(
        conf={
            hst.keys.SYSTEM_PATH: str(sysp),
            hst.keys.NUM_BUCKETS: 4,
            hst.keys.OBS_TRACING_ENABLED: True,
        }
    )
    df = sess.read_parquet(str(d))
    df.create_or_replace_temp_view("lineitem")
    hst.Hyperspace(sess).create_index(
        df,
        hst.CoveringIndexConfig(
            "li_sd",
            ["l_shipdate"],
            ["l_extendedprice", "l_discount", "l_quantity", "l_orderkey"],
        ),
    )
    sess.enable_hyperspace()
    return sess


def _submit_q6_storm(srv):
    """8 threads × 3 requests of q6 literal variants; returns all futures."""
    futures = [[] for _ in range(N_THREADS)]
    errors = []
    start = threading.Barrier(N_THREADS)

    def submitter(k):
        try:
            start.wait()
            for j in range(REQS_PER_THREAD):
                q = TPCH_QUERIES["q6"].replace(
                    "l_quantity < 24", f"l_quantity < {20 + (k + j) % 8}"
                )
                futures[k].append(srv.submit(q, timeout=60))
        except Exception as e:  # surface in the main thread, not as a hang
            errors.append(e)

    threads = [threading.Thread(target=submitter, args=(k,)) for k in range(N_THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    return [f for per in futures for f in per]


def test_q6_concurrent_span_trees_disjoint(lineitem_sess):
    with QueryServer(
        lineitem_sess, workers=N_THREADS, queue_depth=N_THREADS * REQS_PER_THREAD * 2
    ) as srv:
        futs = _submit_q6_storm(srv)
        for f in futs:
            got = f.result(timeout=120)
            assert "revenue" in got
        profiles = [f.profile for f in futs]

    assert len(profiles) == N_THREADS * REQS_PER_THREAD
    seen_ids = set()
    for prof in profiles:
        assert prof is not None and prof.error is None
        nodes = list(prof.root.walk())
        # every request carries its full lifecycle in ITS OWN tree
        names = {sp.name for sp in nodes}
        assert prof.root.name == "request"
        # compile side: first sight of a text parses under the request root;
        # memo/plan-cache hits still record the per-request plan resolution
        assert names & {"parse", "resolve", "resolve-plan"}
        assert names & {"execute", "execute-shared-scan"}  # execute side
        # no cross-request leakage: span objects appear in exactly one tree
        ids = {id(sp) for sp in nodes}
        assert not (ids & seen_ids)
        seen_ids |= ids
        # and the tree is internally consistent: every child's trace is the root's
        assert all(sp.trace is prof.root.trace for sp in nodes)
    # 24 requests -> 24 distinct traces
    assert len({id(p.root.trace) for p in profiles}) == len(profiles)


def test_served_profile_chrome_trace_valid(lineitem_sess, tmp_path):
    with QueryServer(lineitem_sess, workers=2) as srv:
        fut = srv.submit(TPCH_QUERIES["q6"], timeout=60)
        fut.result(timeout=120)
        prof = fut.profile
    doc = prof.chrome_trace()
    _validate_chrome(doc)
    path = str(tmp_path / "q6.trace.json")
    prof.save_chrome_trace(path)
    with open(path) as fh:
        assert json.load(fh)["traceEvents"]
    assert os.path.getsize(path) > 0


def test_profile_history_bounded(lineitem_sess):
    lineitem_sess.conf.set(hst.keys.OBS_PROFILE_HISTORY, 4)
    with QueryServer(lineitem_sess, workers=2) as srv:
        futs = [srv.submit(TPCH_QUERIES["q6"], timeout=60) for _ in range(10)]
        for f in futs:
            f.result(timeout=120)
        kept = srv.last_profiles()
    assert len(kept) == 4  # bounded by hyperspace.obs.profile.history
    assert all(p.root.name == "request" for p in kept)


def test_stats_event_matches_registry_under_load(lineitem_sess):
    """Satellite: the ServingStatsEvent emitted by stats(emit=True) and the
    live registry must agree — equality by construction, asserted under the
    same 8-thread storm."""
    lineitem_sess.conf.set(
        "hyperspace.eventLoggerClass",
        "hyperspace_tpu.telemetry.events.CollectingEventLogger",
    )
    with QueryServer(
        lineitem_sess, workers=N_THREADS, queue_depth=N_THREADS * REQS_PER_THREAD * 2
    ) as srv:
        futs = _submit_q6_storm(srv)
        for f in futs:
            f.result(timeout=120)

        snap = srv.stats(emit=True)
        reg, labels = srv.registry, {"server": srv.server_name}
        from hyperspace_tpu.telemetry.events import get_event_logger

        events = [
            e
            for e in get_event_logger(lineitem_sess).snapshot()
            if e.name == "ServingStatsEvent"
        ]
        assert events, "stats(emit=True) must emit a ServingStatsEvent"
        ev = events[-1]

        # event fields == registry instrument values (same store, no copies)
        assert ev.completed == int(reg.counter("hs_serving_completed_total", **labels).value)
        assert ev.completed == N_THREADS * REQS_PER_THREAD
        assert ev.queue_depth == int(reg.gauge("hs_serving_queue_depth", **labels).value)
        assert ev.rejected == int(reg.gauge("hs_serving_rejected", **labels).value)
        assert ev.plan_cache_hit_rate == pytest.approx(
            reg.gauge("hs_plan_cache_hit_rate", **labels).value
        )
        assert ev.bucket_cache_hit_rate == pytest.approx(
            reg.gauge("hs_bucket_cache_hit_rate", **labels).value
        )
        hist = reg.histogram("hs_serving_latency_seconds", **labels)
        pcts = hist.percentiles()
        assert ev.latency_p50 == pytest.approx(pcts["p50"])
        assert ev.latency_p95 == pytest.approx(pcts["p95"])
        assert ev.latency_p99 == pytest.approx(pcts["p99"])
        assert hist.count == N_THREADS * REQS_PER_THREAD

        # ...and the stats() dict view agrees too
        assert snap["completed"] == ev.completed
        assert snap["queue"]["queued"] == ev.queue_depth
        assert snap["latencySeconds"]["p50"] == pytest.approx(pcts["p50"])

        # the same numbers are scrapeable
        text = srv.prometheus_text()
        assert (
            f'hs_serving_completed_total{{server="{srv.server_name}"}} '
            f"{N_THREADS * REQS_PER_THREAD}" in text
        )

    # shutdown unpublishes nothing the test depends on; the event count made
    # it into the shared substrate as a metric as well
    total = obs_metrics.REGISTRY.counter("hs_events_total", event="ServingStatsEvent")
    assert total.value >= 1


def test_private_registry_when_metrics_disabled(lineitem_sess):
    lineitem_sess.conf.set(hst.keys.OBS_METRICS_ENABLED, False)
    with QueryServer(lineitem_sess, workers=2) as srv:
        assert srv.registry is not obs_metrics.REGISTRY
        fut = srv.submit(TPCH_QUERIES["q6"], timeout=60)
        fut.result(timeout=120)
        assert srv.stats()["completed"] == 1  # accounting still works locally
        labels = {"server": srv.server_name}
        assert srv.registry.counter("hs_serving_completed_total", **labels).value == 1
