"""Cross-process optimistic concurrency on the operation log.

The reference tests concurrent writers at thread level
(IndexLogManagerImplTest races — SURVEY.md §5.2); separate OS processes
exercise the temp-file + atomic-rename protocol with no shared in-process
state at all: exactly one creator wins, losers fail with
ConcurrentModificationException, and the surviving index is consistent.
"""

import os
import subprocess
import sys

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

import hyperspace_tpu as hst

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r'''
import os, sys
sys.path.insert(0, sys.argv[3])
# sitecustomize may import jax at interpreter startup (before this script), so
# setting JAX_PLATFORMS here is too late; update the config object instead —
# four workers racing for the single real TPU chip would hang (see conftest.py)
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import hyperspace_tpu as hst
root, d = sys.argv[1], sys.argv[2]
sess = hst.Session(conf={hst.keys.SYSTEM_PATH: os.path.join(root, "i"), hst.keys.NUM_BUCKETS: 4})
hst.set_session(sess)
hs = hst.Hyperspace(sess)
df = sess.read_parquet(d)
try:
    hs.create_index(df, hst.CoveringIndexConfig("raceIdx", ["k"], ["v"]))
    print("WIN")
except Exception as e:
    print("LOSE", type(e).__name__)
'''


def test_concurrent_creators_single_winner(tmp_path, session):
    d = tmp_path / "data"
    d.mkdir()
    pq.write_table(
        pa.table({"k": np.arange(20_000, dtype=np.int64), "v": np.arange(20_000.0)}),
        d / "p.parquet",
    )
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    sysdir = str(tmp_path)
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), sysdir, str(d), REPO],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for _ in range(4)
    ]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=180)
        assert p.returncode == 0, f"worker crashed: stdout={out!r} stderr={err[-2000:]!r}"
        outs.append(out.strip())
    wins = [o for o in outs if o == "WIN"]
    losses = [o for o in outs if o.startswith("LOSE")]
    assert len(wins) == 1, outs
    assert len(losses) == 3, outs
    # a worker losing the log-id race raises ConcurrentModificationException;
    # one starting after the winner committed fails validate() with a plain
    # "already exists" HyperspaceActionException — both are correct outcomes
    assert all(
        "ConcurrentModificationException" in o or "HyperspaceActionException" in o
        for o in losses
    ), outs

    # the surviving index is consistent and usable from a fresh session —
    # in particular no duplicated rows from two builders sharing a data dir
    sess = hst.Session(conf={hst.keys.SYSTEM_PATH: os.path.join(sysdir, "i"), hst.keys.NUM_BUCKETS: 4})
    hs = hst.Hyperspace(sess)
    df = sess.read_parquet(str(d))
    sess.enable_hyperspace()
    q = df.filter(hst.col("k") == 7).select("v")
    assert "IndexScan" in q.optimized_plan().pretty()
    assert len(q.collect()["v"]) == 1


def _write_sample(d, n=5000):
    pq.write_table(
        pa.table({"k": np.arange(n, dtype=np.int64), "v": np.arange(float(n))}),
        os.path.join(str(d), "p.parquet"),
    )


def test_crashed_create_is_recoverable(tmp_path, session):
    """An abandoned CREATING transient (creator died before any stable entry)
    must not brick the index name: a retrying creator wins the next log id
    and builds into its own exclusively-allocated version dir."""
    import hyperspace_tpu.indexes.covering as cov

    d = tmp_path / "data"
    d.mkdir()
    _write_sample(d)
    session.conf.set(hst.keys.NUM_BUCKETS, 2)
    hs = hst.Hyperspace(session)
    df = session.read_parquet(str(d))

    calls = {"n": 0}
    real_write = cov.CoveringIndex.write

    def crashing_write(self, ctx, df_):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("simulated creator crash mid-build")
        return real_write(self, ctx, df_)

    cov.CoveringIndex.write = crashing_write
    try:
        import pytest as _pytest

        with _pytest.raises(RuntimeError):
            hs.create_index(df, hst.CoveringIndexConfig("crashIdx", ["k"], ["v"]))
        # retry succeeds despite the abandoned CREATING transient
        hs.create_index(df, hst.CoveringIndexConfig("crashIdx", ["k"], ["v"]))
    finally:
        cov.CoveringIndex.write = real_write
    session.enable_hyperspace()
    q = df.filter(hst.col("k") == 7).select("v")
    assert "IndexScan" in q.optimized_plan().pretty()
    assert len(q.collect()["v"]) == 1


def test_failed_action_cleans_allocated_version_dir(tmp_path, session):
    """A failed build deletes the version dir it claimed — repeated failures
    must not accumulate orphan v__=N dirs."""
    import hyperspace_tpu.indexes.covering as cov

    d = tmp_path / "data2"
    d.mkdir()
    _write_sample(d)
    session.conf.set(hst.keys.NUM_BUCKETS, 2)
    hs = hst.Hyperspace(session)
    df = session.read_parquet(str(d))

    real_write = cov.CoveringIndex.write

    def failing_write(self, ctx, df_):
        raise RuntimeError("boom")

    cov.CoveringIndex.write = failing_write
    try:
        import pytest as _pytest

        for _ in range(3):
            with _pytest.raises(RuntimeError):
                hs.create_index(df, hst.CoveringIndexConfig("leakIdx", ["k"], ["v"]))
    finally:
        cov.CoveringIndex.write = real_write
    sysp = session.conf.get(hst.keys.SYSTEM_PATH)
    idx_dir = os.path.join(sysp, "leakIdx")
    version_dirs = [n for n in os.listdir(idx_dir) if n.startswith("v__=")] if os.path.isdir(idx_dir) else []
    assert version_dirs == [], version_dirs
    # and the name still works afterwards
    hs.create_index(df, hst.CoveringIndexConfig("leakIdx", ["k"], ["v"]))
