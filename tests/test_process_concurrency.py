"""Cross-process optimistic concurrency on the operation log.

The reference tests concurrent writers at thread level
(IndexLogManagerImplTest races — SURVEY.md §5.2); separate OS processes
exercise the temp-file + atomic-rename protocol with no shared in-process
state at all: exactly one creator wins, losers fail with
ConcurrentModificationException, and the surviving index is consistent.
"""

import os
import subprocess
import sys

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

import hyperspace_tpu as hst

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r'''
import os, sys
sys.path.insert(0, sys.argv[3])
os.environ["JAX_PLATFORMS"] = "cpu"
import hyperspace_tpu as hst
root, d = sys.argv[1], sys.argv[2]
sess = hst.Session(conf={hst.keys.SYSTEM_PATH: os.path.join(root, "i"), hst.keys.NUM_BUCKETS: 4})
hst.set_session(sess)
hs = hst.Hyperspace(sess)
df = sess.read_parquet(d)
try:
    hs.create_index(df, hst.CoveringIndexConfig("raceIdx", ["k"], ["v"]))
    print("WIN")
except Exception as e:
    print("LOSE", type(e).__name__)
'''


def test_concurrent_creators_single_winner(tmp_path, session):
    d = tmp_path / "data"
    d.mkdir()
    pq.write_table(
        pa.table({"k": np.arange(20_000, dtype=np.int64), "v": np.arange(20_000.0)}),
        d / "p.parquet",
    )
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    sysdir = str(tmp_path)
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), sysdir, str(d), REPO],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for _ in range(4)
    ]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=180)
        assert p.returncode == 0, f"worker crashed: stdout={out!r} stderr={err[-2000:]!r}"
        outs.append(out.strip())
    wins = [o for o in outs if o == "WIN"]
    losses = [o for o in outs if o.startswith("LOSE")]
    assert len(wins) == 1, outs
    assert len(losses) == 3, outs
    # a worker losing the log-id race raises ConcurrentModificationException;
    # one starting after the winner committed fails validate() with a plain
    # "already exists" HyperspaceActionException — both are correct outcomes
    assert all(
        "ConcurrentModificationException" in o or "HyperspaceActionException" in o
        for o in losses
    ), outs

    # the surviving index is consistent and usable from a fresh session
    sess = hst.Session(conf={hst.keys.SYSTEM_PATH: os.path.join(sysdir, "i"), hst.keys.NUM_BUCKETS: 4})
    hs = hst.Hyperspace(sess)
    df = sess.read_parquet(str(d))
    sess.enable_hyperspace()
    q = df.filter(hst.col("k") == 7).select("v")
    assert "IndexScan" in q.optimized_plan().pretty()
    assert len(q.collect()["v"]) == 1
