"""JoinIndexRule scenario matrix, porting the reference's JoinIndexRuleTest
breadth (614 lines — ref:
src/test/scala/com/microsoft/hyperspace/index/covering/JoinIndexRuleTest.scala:120-570):
non-equality / OR / literal join conditions, one-to-one attribute mapping,
composite keys in every predicate order, repeated predicates, and swapped
attributes."""


import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.plan import logical as L
from hyperspace_tpu.plan.expr import col, lit


@pytest.fixture()
def hs(session):
    return hst.Hyperspace(session)


@pytest.fixture()
def two_sides(session, hs, tmp_path):
    session.conf.set(hst.keys.NUM_BUCKETS, 4)
    rng = np.random.default_rng(12)
    l, r = tmp_path / "jl", tmp_path / "jr"
    l.mkdir(), r.mkdir()
    n = 600
    pq.write_table(
        pa.table(
            {
                "t1c1": rng.integers(0, 40, n).astype(np.int64),
                "t1c2": np.array([f"s{v}" for v in rng.integers(0, 10, n)]),
                "t1c3": rng.integers(0, 20, n).astype(np.int64),
                "t1c4": rng.standard_normal(n),
            }
        ),
        l / "p.parquet",
    )
    pq.write_table(
        pa.table(
            {
                "t2c1": rng.integers(0, 40, n).astype(np.int64),
                "t2c2": np.array([f"s{v}" for v in rng.integers(0, 10, n)]),
                "t2c3": rng.integers(0, 20, n).astype(np.int64),
                "t2c4": rng.standard_normal(n),
            }
        ),
        r / "p.parquet",
    )
    ldf, rdf = session.read_parquet(str(l)), session.read_parquet(str(r))
    return ldf, rdf


from conftest import check_answer, index_scans as scans  # noqa: E402


class TestEligibility:
    def test_applies_with_matching_indexes(self, session, hs, two_sides):
        ldf, rdf = two_sides
        hs.create_index(ldf, hst.CoveringIndexConfig("e1L", ["t1c1"], ["t1c4"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("e1R", ["t2c1"], ["t2c4"]))
        session.enable_hyperspace()
        q = ldf.join(rdf, on=col("t1c1") == col("t2c1")).select("t1c4", "t2c4")
        assert len(scans(q)) == 2, q.optimized_plan().pretty()
        check_answer(session, q)

    def test_no_rewrite_for_non_equality_condition(self, session, hs, two_sides):
        """(ref: JoinIndexRuleTest:171-186)"""
        ldf, rdf = two_sides
        hs.create_index(ldf, hst.CoveringIndexConfig("neL", ["t1c1"], ["t1c4"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("neR", ["t2c1"], ["t2c4"]))
        session.enable_hyperspace()
        # a non-equality condition plans but is never rewritten (and the
        # executor rejects it at run time: only conjunctive equi-joins exist)
        q = ldf.join(rdf, on=col("t1c1") > col("t2c1"), how="inner")
        assert len(scans(q)) == 0, q.optimized_plan().pretty()
        with pytest.raises(NotImplementedError, match="equi-join"):
            q.collect()

    def test_no_rewrite_for_or_condition(self, session, hs, two_sides):
        """(ref: JoinIndexRuleTest:187-202)"""
        ldf, rdf = two_sides
        session.enable_hyperspace()
        q = ldf.join(
            rdf, on=(col("t1c1") == col("t2c1")) | (col("t1c3") == col("t2c3"))
        )
        assert len(scans(q)) == 0, q.optimized_plan().pretty()
        with pytest.raises(NotImplementedError, match="equi-join"):
            q.collect()

    def test_no_rewrite_for_literal_condition(self, session, hs, two_sides):
        """(ref: JoinIndexRuleTest:203-218)"""
        ldf, rdf = two_sides
        session.enable_hyperspace()
        q = ldf.join(rdf, on=col("t1c1") == lit(5))
        assert len(scans(q)) == 0, q.optimized_plan().pretty()
        with pytest.raises(NotImplementedError, match="equi-join"):
            q.collect()

    def test_no_rewrite_when_one_side_unindexed(self, session, hs, two_sides):
        """(ref: JoinIndexRuleTest:219-239)"""
        ldf, rdf = two_sides
        hs.create_index(ldf, hst.CoveringIndexConfig("halfL", ["t1c1"], ["t1c4"]))
        session.enable_hyperspace()
        q = ldf.join(rdf, on=col("t1c1") == col("t2c1")).select("t1c4", "t2c4")
        assert len(scans(q)) == 0, q.optimized_plan().pretty()
        check_answer(session, q)

    def test_no_rewrite_when_index_missing_required_column(self, session, hs, two_sides):
        ldf, rdf = two_sides
        hs.create_index(ldf, hst.CoveringIndexConfig("mcL", ["t1c1"], ["t1c4"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("mcR", ["t2c1"], ["t2c4"]))
        session.enable_hyperspace()
        # t2c3 is not covered by mcR -> no rewrite on either side
        q = ldf.join(rdf, on=col("t1c1") == col("t2c1")).select("t1c4", "t2c3")
        assert len(scans(q)) == 0, q.optimized_plan().pretty()
        check_answer(session, q)


class TestCompositeKeys:
    """(ref: JoinIndexRuleTest:403-521 composite AND equi-joins)"""

    def _indexes(self, hs, ldf, rdf, tag):
        hs.create_index(
            ldf, hst.CoveringIndexConfig(f"{tag}L", ["t1c1", "t1c2"], ["t1c4"])
        )
        hs.create_index(
            rdf, hst.CoveringIndexConfig(f"{tag}R", ["t2c1", "t2c2"], ["t2c4"])
        )

    def test_composite_and_join(self, session, hs, two_sides):
        ldf, rdf = two_sides
        self._indexes(hs, ldf, rdf, "ca")
        session.enable_hyperspace()
        q = ldf.join(
            rdf, on=(col("t1c1") == col("t2c1")) & (col("t1c2") == col("t2c2"))
        ).select("t1c4", "t2c4")
        assert len(scans(q)) == 2, q.optimized_plan().pretty()
        check_answer(session, q)

    def test_composite_predicate_order_flipped(self, session, hs, two_sides):
        """Predicates in the opposite order of the index's column order
        still match (ref: :419-435)."""
        ldf, rdf = two_sides
        self._indexes(hs, ldf, rdf, "cf")
        session.enable_hyperspace()
        q = ldf.join(
            rdf, on=(col("t1c2") == col("t2c2")) & (col("t1c1") == col("t2c1"))
        ).select("t1c4", "t2c4")
        assert len(scans(q)) == 2, q.optimized_plan().pretty()
        check_answer(session, q)

    def test_composite_swapped_attributes(self, session, hs, two_sides):
        """Each equality written right-side-first (ref: :436-451)."""
        ldf, rdf = two_sides
        self._indexes(hs, ldf, rdf, "cs")
        session.enable_hyperspace()
        q = ldf.join(
            rdf, on=(col("t2c1") == col("t1c1")) & (col("t2c2") == col("t1c2"))
        ).select("t1c4", "t2c4")
        assert len(scans(q)) == 2, q.optimized_plan().pretty()
        check_answer(session, q)

    def test_repeated_predicates_dedupe(self, session, hs, two_sides):
        """The same equality repeated must not break matching (ref: :506-521)."""
        ldf, rdf = two_sides
        self._indexes(hs, ldf, rdf, "cr")
        session.enable_hyperspace()
        q = ldf.join(
            rdf,
            on=(col("t1c1") == col("t2c1"))
            & (col("t1c2") == col("t2c2"))
            & (col("t1c1") == col("t2c1")),
        ).select("t1c4", "t2c4")
        assert len(scans(q)) == 2, q.optimized_plan().pretty()
        check_answer(session, q)

    def test_no_one_to_one_mapping_rejected(self, session, hs, two_sides):
        """t1c1 equated with BOTH t2c1 and t2c3: not a one-to-one attribute
        mapping -> no rewrite (ref: :452-505)."""
        ldf, rdf = two_sides
        self._indexes(hs, ldf, rdf, "cm")
        session.enable_hyperspace()
        q = ldf.join(
            rdf, on=(col("t1c1") == col("t2c1")) & (col("t1c1") == col("t2c3"))
        ).select("t1c4", "t2c4")
        assert len(scans(q)) == 0, q.optimized_plan().pretty()
        check_answer(session, q)

    def test_subset_key_join_not_served_by_composite_index(self, session, hs, two_sides):
        """A single-key join cannot use a two-key bucketed index (bucketing
        hashes both columns; ref: JoinColumnFilter indexed == join cols)."""
        ldf, rdf = two_sides
        self._indexes(hs, ldf, rdf, "ss")
        session.enable_hyperspace()
        q = ldf.join(rdf, on=col("t1c1") == col("t2c1")).select("t1c4", "t2c4")
        assert len(scans(q)) == 0, q.optimized_plan().pretty()
        check_answer(session, q)


class TestCaseAndSelfJoin:
    def test_case_insensitive_key_matching(self, session, hs, two_sides):
        """(ref: JoinIndexRuleTest:130-141)"""
        ldf, rdf = two_sides
        hs.create_index(ldf, hst.CoveringIndexConfig("ciL", ["T1C1"], ["t1c4"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("ciR", ["T2C1"], ["t2c4"]))
        session.enable_hyperspace()
        q = ldf.join(rdf, on=col("t1c1") == col("T2C1")).select("t1c4", "t2c4")
        assert len(scans(q)) == 2, q.optimized_plan().pretty()
        check_answer(session, q)

    def test_self_join_uses_same_index_twice(self, session, hs, two_sides):
        ldf, _ = two_sides
        hs.create_index(ldf, hst.CoveringIndexConfig("selfI", ["t1c1"], ["t1c4"]))
        session.enable_hyperspace()
        q = ldf.join(ldf, on=col("t1c1") == col("t1c1")).select("t1c4", "t1c4#r")
        assert len(scans(q)) == 2, q.optimized_plan().pretty()
        check_answer(session, q)
