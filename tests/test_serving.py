"""Unit tests for the serving runtime (hyperspace_tpu/serving/).

Each component is exercised in isolation — plan cache tiers and eviction,
admission backpressure, bucket cache + prefetch, metrics, micro-batch
decomposition — plus QueryServer integration against ``collect()`` ground
truth. Concurrency/throughput behavior lives in test_serving_stress.py.
"""

import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.serving import (
    AdmissionController,
    AdmissionRejected,
    BucketCache,
    PlanCache,
    QueryServer,
    RequestTimeout,
    ServerClosed,
    ServingMetrics,
    plan_fingerprint,
    session_token,
)


@pytest.fixture()
def simple(tmp_path):
    n = 500
    pq.write_table(
        pa.table(
            {
                "id": np.arange(n, dtype=np.int64),
                "name": np.array([f"n{i % 11}" for i in range(n)]),
                "price": (np.arange(n, dtype=np.int64) * 7) % 100,
            }
        ),
        str(tmp_path / "t.parquet"),
    )
    sess = hst.Session()
    sess.read_parquet(str(tmp_path / "t.parquet")).create_or_replace_temp_view("t")
    return sess


# --- plan cache --------------------------------------------------------------


def test_plan_cache_param_tier_hits(simple):
    cache = PlanCache(max_entries=8)
    tok = session_token(simple, False)
    p45 = simple.sql("SELECT name FROM t WHERE price > 45").plan
    f45 = plan_fingerprint(p45)
    assert cache.lookup(tok, f45) is None  # cold
    cache.insert(tok, f45, p45)

    f40 = plan_fingerprint(simple.sql("SELECT name FROM t WHERE price > 40").plan)
    hit = cache.lookup(tok, f40)
    assert hit is not None
    bound, entry = hit
    assert entry.parameterizable
    assert plan_fingerprint(bound).exact == f40.exact  # literals rebound
    s = cache.stats()
    assert s["paramHits"] == 1 and s["misses"] == 1 and s["entries"] == 1


def test_plan_cache_session_token_separates_modes(simple):
    cache = PlanCache()
    p = simple.sql("SELECT name FROM t WHERE price > 45").plan
    fp = plan_fingerprint(p)
    cache.insert(session_token(simple, False), fp, p)
    # same plan under hyperspace-on token must NOT reuse the off-mode template
    assert cache.lookup(session_token(simple, True), fp) is None


def test_plan_cache_eviction_accounting(simple):
    cache = PlanCache(max_entries=2)
    tok = session_token(simple, False)
    texts = [
        "SELECT name FROM t WHERE price > 1",
        "SELECT id FROM t WHERE price > 1",
        "SELECT price FROM t WHERE id > 1",
    ]
    for q in texts:
        p = simple.sql(q).plan
        cache.insert(tok, plan_fingerprint(p), p)
    s = cache.stats()
    assert s["entries"] == 2 and s["evictions"] == 1
    assert len(cache) == 2


def test_plan_cache_subquery_goes_exact_tier(simple):
    cache = PlanCache()
    tok = session_token(simple, False)
    q = "SELECT name FROM t WHERE price > (SELECT avg(price) FROM t WHERE id < 100)"
    p = simple.sql(q).plan
    fp = plan_fingerprint(p)
    entry = cache.insert(tok, fp, p)
    assert not entry.parameterizable
    # verbatim repeat hits the exact tier
    hit = cache.lookup(tok, plan_fingerprint(simple.sql(q).plan))
    assert hit is not None
    assert cache.stats()["exactHits"] == 1


# --- admission ---------------------------------------------------------------


def test_admission_rejects_on_overflow():
    adm = AdmissionController(depth=2, default_timeout=None)
    adm.submit("a")
    adm.submit("b")
    with pytest.raises(AdmissionRejected):
        adm.submit("c")
    s = adm.stats()
    assert s == {"depth": 2, "queued": 2, "submitted": 2, "rejected": 1, "timeouts": 0}
    assert adm.take() == "a" and adm.take_nowait() == "b" and adm.take_nowait() is None


def test_admission_deadlines():
    adm = AdmissionController(depth=1, default_timeout=5.0)
    assert adm.deadline_for(None) > time.monotonic()
    assert adm.deadline_for(0.1) < time.monotonic() + 1.0
    assert AdmissionController(depth=1, default_timeout=None).deadline_for(None) is None
    with pytest.raises(ValueError):
        AdmissionController(depth=0, default_timeout=None)


# --- bucket cache ------------------------------------------------------------


def _write_files(tmp_path, k, rows=200):
    files = []
    for i in range(k):
        f = str(tmp_path / f"b{i}.parquet")
        pq.write_table(
            pa.table({"v": np.arange(i * rows, (i + 1) * rows, dtype=np.int64)}), f
        )
        files.append(f)
    return files


def test_bucket_cache_hit_miss_and_freeze(tmp_path):
    files = _write_files(tmp_path, 2)
    bc = BucketCache(cap_bytes=1 << 20)
    a = bc.read(files, ["v"])
    b = bc.read(files, ["v"])
    assert np.array_equal(a["v"], b["v"]) and len(a["v"]) == 400
    s = bc.stats()
    assert s["hits"] == 1 and s["misses"] == 1 and s["hitRate"] == 0.5
    with pytest.raises(ValueError):
        b["v"][0] = 99  # cached arrays are frozen


def test_bucket_cache_byte_budget_evicts(tmp_path):
    files = _write_files(tmp_path, 4, rows=500)
    bc = BucketCache(cap_bytes=int(500 * 8 * 1.5))  # fits ~one file's batch
    for f in files:
        bc.read([f], ["v"])
    s = bc.stats()
    assert s["evictions"] >= 2
    assert s["bytes"] <= s["capBytes"]


def test_bucket_cache_prefetch_lands(tmp_path):
    files = _write_files(tmp_path, 1)
    bc = BucketCache(cap_bytes=1 << 20, prefetch_workers=1)
    assert bc.prefetch(files, ["v"]) is True
    deadline = time.monotonic() + 10
    while bc.stats()["prefetchCompleted"] < 1 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert bc.stats()["prefetchCompleted"] == 1
    before = bc.stats()["hits"]
    bc.read(files, ["v"])
    assert bc.stats()["hits"] == before + 1  # request path found it resident
    assert bc.prefetch(files, ["v"]) is False  # already cached: no refetch
    bc.shutdown()


# --- metrics -----------------------------------------------------------------


def test_metrics_percentiles_and_counters():
    m = ServingMetrics(latency_window=128)
    assert m.latency_percentiles() == {"p50": None, "p95": None, "p99": None}
    for v in np.linspace(0.001, 0.1, 100):
        m.observe(float(v))
    m.observe(1.0, error=True)
    m.observe_batch(4)
    p = m.latency_percentiles()
    assert p["p50"] <= p["p95"] <= p["p99"]
    snap = m.snapshot()
    assert snap["completed"] == 100 and snap["errors"] == 1
    assert snap["batches"] == 1 and snap["batchedRequests"] == 4


# --- telemetry thread safety -------------------------------------------------


def test_collecting_logger_concurrent_appends():
    from hyperspace_tpu.telemetry.events import CollectingEventLogger, HyperspaceEvent

    logger = CollectingEventLogger()
    n_threads, per_thread = 8, 250

    def work():
        for _ in range(per_thread):
            logger.log_event(HyperspaceEvent(message="x"))

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(logger.events) == n_threads * per_thread
    assert len(logger.snapshot()) == n_threads * per_thread
    # events stays a real list: in-place clear() (used by existing tests) works
    logger.events.clear()
    assert logger.snapshot() == []


# --- context-local hyperspace toggle ----------------------------------------


def test_hyperspace_scope_is_thread_local(simple):
    simple.enable_hyperspace()
    seen = {}

    def other_thread():
        seen["other"] = simple.hyperspace_enabled

    with simple.with_hyperspace_disabled():
        assert simple.hyperspace_enabled is False
        t = threading.Thread(target=other_thread)
        t.start()
        t.join()
    assert simple.hyperspace_enabled is True
    # a scope in one thread never leaks into another: the other thread saw
    # the session default, not this thread's override
    assert seen["other"] is True
    simple.disable_hyperspace()
    assert simple.hyperspace_enabled is False


def test_hyperspace_scope_nests_and_restores_on_error(simple):
    simple.enable_hyperspace()
    with simple.hyperspace_scope(False):
        with simple.hyperspace_scope(True):
            assert simple.hyperspace_enabled is True
        assert simple.hyperspace_enabled is False
    with pytest.raises(RuntimeError):
        with simple.with_hyperspace_disabled():
            raise RuntimeError("boom")
    assert simple.hyperspace_enabled is True


# --- micro-batch decomposition ----------------------------------------------


def test_shared_scan_ops_shapes(simple):
    from hyperspace_tpu.serving.batcher import shared_scan_ops

    chain = simple.sql("SELECT name FROM t WHERE price > 5").plan
    got = shared_scan_ops(chain)
    assert got is not None
    ops, leaf = got
    assert [k for k, _ in ops] == ["project", "filter"]
    # no filter -> nothing literal-varying to share
    assert shared_scan_ops(simple.sql("SELECT name FROM t").plan) is None
    # one aggregate may cap the chain (its filters sit below it)
    agg = shared_scan_ops(simple.sql("SELECT count(*) AS c FROM t WHERE price > 5").plan)
    assert agg is not None
    assert "aggregate" in [k for k, _ in agg[0]]


def test_execute_shared_scan_matches_individual(simple):
    from hyperspace_tpu.serving.batcher import execute_shared_scan, shared_scan_ops

    template = simple.sql("SELECT name, id FROM t WHERE price > 45").plan
    ops, leaf = shared_scan_ops(template)
    bound = [simple.sql(f"SELECT name, id FROM t WHERE price > {v}").plan for v in (45, 20, 80)]
    batches = execute_shared_scan(simple, ops, leaf, bound)
    for v, got in zip((45, 20, 80), batches):
        want = simple.sql(f"SELECT name, id FROM t WHERE price > {v}").collect()
        assert np.array_equal(got["name"], want["name"])
        assert np.array_equal(got["id"], want["id"])


# --- QueryServer integration -------------------------------------------------


def test_server_matches_collect_and_relabels(simple):
    with QueryServer(simple, workers=2) as srv:
        r1 = srv.query("SELECT name FROM t WHERE price > 45")
        r2 = srv.query("SELECT name FROM t WHERE price > 20")
        r3 = srv.query("SELECT name AS m FROM t WHERE price > 20")
        want45 = simple.sql("SELECT name FROM t WHERE price > 45").collect()
        want20 = simple.sql("SELECT name FROM t WHERE price > 20").collect()
        assert np.array_equal(r1["name"], want45["name"])
        assert np.array_equal(r2["name"], want20["name"])
        assert list(r3.keys()) == ["m"] and np.array_equal(r3["m"], want20["name"])
        s = srv.stats()
        assert s["planCache"]["paramHits"] >= 2  # r2 and r3 bound the r1 template
        assert s["queue"]["submitted"] == 3 and s["queue"]["rejected"] == 0
        assert s["completed"] == 3 and s["errors"] == 0


def test_server_accepts_dataframe_and_exact_repeat(simple):
    with QueryServer(simple, workers=1) as srv:
        df = simple.sql("SELECT id FROM t WHERE price < 10")
        a = srv.query(df)
        b = srv.query("SELECT id FROM t WHERE price < 10")
        want = df.collect()
        assert np.array_equal(a["id"], want["id"]) and np.array_equal(b["id"], want["id"])
        assert srv.stats()["planCache"]["hits"] >= 1


def test_server_bad_query_resolves_future_with_error(simple, tmp_path):
    import os

    doomed = str(tmp_path / "gone.parquet")
    pq.write_table(pa.table({"v": np.arange(5, dtype=np.int64)}), doomed)
    simple.read_parquet(doomed).create_or_replace_temp_view("gone")
    with QueryServer(simple, workers=1) as srv:
        # parse errors surface synchronously at submit time
        with pytest.raises(Exception):
            srv.submit("SELECT nope FROM t WHERE price > 1")
        # execution errors resolve the future, and the worker survives them
        df = simple.sql("SELECT v FROM gone WHERE v > 1")
        os.remove(doomed)
        with pytest.raises(Exception):
            srv.query(df)
        got = srv.query("SELECT id FROM t WHERE price > 90")
        want = simple.sql("SELECT id FROM t WHERE price > 90").collect()
        assert np.array_equal(got["id"], want["id"])
        assert srv.stats()["errors"] >= 1


def test_server_overflow_rejects_and_shutdown_drains(simple):
    # workers=0: nothing consumes the queue, so overflow is deterministic
    srv = QueryServer(simple, workers=0, queue_depth=3).start()
    futs = [srv.submit(f"SELECT id FROM t WHERE price > {i}") for i in range(3)]
    with pytest.raises(AdmissionRejected):
        srv.submit("SELECT id FROM t WHERE price > 99")
    assert srv.stats()["queue"]["rejected"] == 1
    srv.shutdown()
    for f in futs:  # no future is left dangling after shutdown
        with pytest.raises(ServerClosed):
            f.result(timeout=1)
    with pytest.raises(ServerClosed):
        srv.submit("SELECT id FROM t WHERE price > 1")


def test_server_rejection_emits_telemetry(tmp_path):
    pq.write_table(pa.table({"v": np.arange(10, dtype=np.int64)}), str(tmp_path / "x.parquet"))
    sess = hst.Session(
        conf={hst.keys.EVENT_LOGGER_CLASS: "hyperspace_tpu.telemetry.events.CollectingEventLogger"}
    )
    sess.read_parquet(str(tmp_path / "x.parquet")).create_or_replace_temp_view("x")
    logger = hst.telemetry.events.get_event_logger(sess)
    logger.reset()
    srv = QueryServer(sess, workers=0, queue_depth=1).start()
    try:
        srv.submit("SELECT v FROM x WHERE v > 1")
        with pytest.raises(AdmissionRejected):
            srv.submit("SELECT v FROM x WHERE v > 2")
        rejections = [e for e in logger.snapshot() if e.name == "ServingRejectionEvent"]
        assert len(rejections) == 1 and rejections[0].queue_depth == 1
        srv.stats(emit=True)
        stats_events = [e for e in logger.snapshot() if e.name == "ServingStatsEvent"]
        assert len(stats_events) == 1
        assert stats_events[0].rejected == 1
    finally:
        srv.shutdown()
        logger.reset()


def test_server_timeout_in_queue(simple):
    with QueryServer(simple, workers=1) as srv:
        fut = srv.submit("SELECT id FROM t WHERE price > 7", timeout=0.0)
        with pytest.raises(RequestTimeout):
            fut.result(timeout=10)
        assert srv.stats()["queue"]["timeouts"] >= 1


def test_server_rejects_unknown_option(simple):
    with pytest.raises(TypeError):
        QueryServer(simple, wrokers=2)


def test_serving_conf_defaults(simple):
    conf = simple.conf
    assert conf.serving_queue_depth == 64
    assert conf.serving_workers == 4
    assert conf.serving_default_timeout_seconds == 30.0
    assert conf.serving_plan_cache_enabled is True
    assert conf.serving_plan_cache_max_entries == 256
    assert conf.serving_micro_batch_enabled is True
    assert conf.serving_micro_batch_max_requests == 16
    assert conf.serving_micro_batch_max_wait_ms == 2.0
    assert conf.serving_bucket_cache_bytes == 1 << 30
    assert conf.serving_prefetch_enabled is True
    assert conf.serving_prefetch_workers == 2


def test_server_reads_conf_keys(tmp_path):
    pq.write_table(pa.table({"v": np.arange(10, dtype=np.int64)}), str(tmp_path / "x.parquet"))
    sess = hst.Session(
        conf={
            hst.keys.SERVING_QUEUE_DEPTH: 7,
            hst.keys.SERVING_WORKERS: 1,
            hst.keys.SERVING_PLAN_CACHE_ENABLED: False,
            hst.keys.SERVING_BUCKET_CACHE_BYTES: 12345,
        }
    )
    srv = QueryServer(sess)
    assert srv.admission.depth == 7
    assert srv.workers_n == 1
    assert srv.plan_cache_enabled is False
    assert srv.bucket_cache.stats()["capBytes"] == 12345
    assert "planCache" not in srv.metrics.snapshot(
        admission=srv.admission, plan_cache=None, bucket_cache=srv.bucket_cache
    )
