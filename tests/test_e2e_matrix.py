"""E2E scenario matrix, porting the reference suite's remaining axes.

The reference's E2EHyperspaceRulesTest (1,109 lines) sweeps enable/disable
sequencing, case sensitivity in queries AND index configs, catalog/view
sources, aliased-column limits, filter-subquery join children, globbing ×
hybrid scan, and refresh-then-query per refresh mode; its source-integration
suites repeat the refresh matrix on Delta and Iceberg
(ref: src/test/scala/com/microsoft/hyperspace/index/E2EHyperspaceRulesTest.scala:75-1016,
DeltaLakeIntegrationTest.scala, IcebergIntegrationTest.scala).

Every scenario here asserts the two reference invariants: the rewritten plan
scans index files (verifyIndexUsage), and results equal the no-index run
(checkAnswer).
"""

import glob
import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.plan import logical as L


@pytest.fixture()
def hs(session):
    return hst.Hyperspace(session)


from conftest import check_answer, index_scans  # noqa: E402


def write_sample(d, n=400, seed=0, start=0):
    rng = np.random.default_rng(seed)
    pq.write_table(
        pa.table(
            {
                "Query": np.array([f"q{v}" for v in rng.integers(0, 30, n)]),
                "imprs": rng.integers(0, 100, n).astype(np.int64),
                "clicks": rng.integers(0, 10, n).astype(np.int64),
            }
        ),
        os.path.join(d, f"part-{start:05d}.parquet"),
    )


class TestEnableDisableSequencing:
    """(ref: E2EHyperspaceRulesTest:75-123, 403-519)"""

    def test_enable_disable_enable(self, session, hs, tmp_path):
        d = tmp_path / "seq"
        d.mkdir()
        write_sample(str(d))
        df = session.read_parquet(str(d))
        hs.create_index(df, hst.CoveringIndexConfig("seqIdx", ["Query"], ["imprs"]))
        q = df.filter(hst.col("Query") == "q3").select("imprs")
        session.enable_hyperspace()
        assert index_scans(q)
        session.disable_hyperspace()
        assert not index_scans(q)
        session.enable_hyperspace()
        assert index_scans(q)

    def test_is_hyperspace_enabled(self, session, hs, tmp_path):
        assert not session.is_hyperspace_enabled()
        session.enable_hyperspace()
        assert session.is_hyperspace_enabled()
        session.disable_hyperspace()
        assert not session.is_hyperspace_enabled()

    def test_double_enable_is_idempotent(self, session, hs, tmp_path):
        d = tmp_path / "dbl"
        d.mkdir()
        write_sample(str(d))
        df = session.read_parquet(str(d))
        hs.create_index(df, hst.CoveringIndexConfig("dblIdx", ["Query"], ["imprs"]))
        session.enable_hyperspace()
        session.enable_hyperspace()
        q = df.filter(hst.col("Query") == "q1").select("imprs")
        assert len(index_scans(q)) == 1
        check_answer(session, q)


class TestCaseSensitivity:
    """Differently-cased column names in configs, queries, and SQL all
    resolve to the same index (ref: E2EHyperspaceRulesTest:124-228)."""

    def test_filter_query_case_insensitive(self, session, hs, tmp_path):
        d = tmp_path / "cs1"
        d.mkdir()
        write_sample(str(d))
        df = session.read_parquet(str(d))
        # config uses different casing than the data ("QUERY" vs "Query")
        hs.create_index(df, hst.CoveringIndexConfig("csIdx", ["QUERY"], ["IMPRS"]))
        q = df.filter(hst.col("query") == "q7").select("imprs")
        session.enable_hyperspace()
        assert index_scans(q), q.optimized_plan().pretty()
        check_answer(session, q)

    def test_join_query_case_insensitive(self, session, hs, tmp_path):
        l, r = tmp_path / "cs_l", tmp_path / "cs_r"
        l.mkdir(), r.mkdir()
        write_sample(str(l), seed=1)
        pq.write_table(
            pa.table({"query": np.array([f"q{i}" for i in range(30)]),
                      "budget": np.arange(30.0)}),
            r / "p.parquet",
        )
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        ldf, rdf = session.read_parquet(str(l)), session.read_parquet(str(r))
        hs.create_index(ldf, hst.CoveringIndexConfig("csJL", ["Query"], ["imprs"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("csJR", ["QUERY"], ["budget"]))
        session.enable_hyperspace()
        q = ldf.join(rdf, on=hst.col("QUERY") == hst.col("query")).select("imprs", "budget")
        assert len(index_scans(q)) == 2, q.optimized_plan().pretty()
        check_answer(session, q)

    def test_sql_case_insensitive(self, session, hs, tmp_path):
        d = tmp_path / "cs2"
        d.mkdir()
        write_sample(str(d))
        df = session.read_parquet(str(d))
        df.create_or_replace_temp_view("casey")
        hs.create_index(df, hst.CoveringIndexConfig("csSql", ["Query"], ["imprs"]))
        session.enable_hyperspace()
        q = session.sql("SELECT IMPRS FROM casey WHERE QUERY = 'q2'")
        assert index_scans(q), q.optimized_plan().pretty()
        check_answer(session, q)


class TestViewSources:
    """Temp views as query sources (the reference's catalog temp
    tables/views scenario, E2EHyperspaceRulesTest:266-288)."""

    def test_join_on_temp_views(self, session, hs, tmp_path):
        l, r = tmp_path / "v_l", tmp_path / "v_r"
        l.mkdir(), r.mkdir()
        write_sample(str(l), seed=2)
        pq.write_table(
            pa.table({"Query": np.array([f"q{i}" for i in range(30)]),
                      "rank": np.arange(30, dtype=np.int64)}),
            r / "p.parquet",
        )
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        ldf, rdf = session.read_parquet(str(l)), session.read_parquet(str(r))
        ldf.create_or_replace_temp_view("clicks_v")
        rdf.create_or_replace_temp_view("ranks_v")
        hs.create_index(ldf, hst.CoveringIndexConfig("vJL", ["Query"], ["clicks"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("vJR", ["Query"], ["rank"]))
        session.enable_hyperspace()
        q = session.sql(
            "SELECT clicks, rank FROM clicks_v c JOIN ranks_v r ON c.Query = r.Query"
        )
        assert len(index_scans(q)) == 2, q.optimized_plan().pretty()
        check_answer(session, q)

    def test_view_over_filtered_frame_not_rewritten_wrongly(self, session, hs, tmp_path):
        d = tmp_path / "v2"
        d.mkdir()
        write_sample(str(d))
        df = session.read_parquet(str(d))
        hs.create_index(df, hst.CoveringIndexConfig("vF", ["Query"], ["imprs"]))
        filtered = df.filter(hst.col("imprs") > 50)
        filtered.create_or_replace_temp_view("hot")
        session.enable_hyperspace()
        # index does NOT cover 'clicks': the view query must stay unrewritten
        q = session.sql("SELECT clicks FROM hot WHERE Query = 'q1'")
        assert not index_scans(q)
        check_answer(session, q)


class TestJoinShapes:
    def test_join_children_with_filters(self, session, hs, tmp_path):
        """Both join children are filter sub-queries
        (ref: E2EHyperspaceRulesTest:372-402)."""
        l, r = tmp_path / "f_l", tmp_path / "f_r"
        l.mkdir(), r.mkdir()
        write_sample(str(l), seed=3)
        write_sample(str(r), seed=4)
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        ldf, rdf = session.read_parquet(str(l)), session.read_parquet(str(r))
        hs.create_index(ldf, hst.CoveringIndexConfig("fJL", ["Query"], ["imprs", "clicks"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("fJR", ["Query"], ["imprs", "clicks"]))
        session.enable_hyperspace()
        q = (
            ldf.filter(hst.col("clicks") >= 2)
            .join(rdf.filter(hst.col("clicks") <= 4), on="Query")
            .select("Query", "imprs", "imprs#r")
        )
        assert len(index_scans(q)) == 2, q.optimized_plan().pretty()
        check_answer(session, q)

    def test_aliased_columns_not_supported(self, session, hs, tmp_path):
        """A join over renamed columns is not rewritten (the reference's
        'alias columns is not supported', E2EHyperspaceRulesTest:229-265)."""
        from hyperspace_tpu.plan.dataframe import DataFrame
        from hyperspace_tpu.plan.logical import Rename

        l, r = tmp_path / "a_l", tmp_path / "a_r"
        l.mkdir(), r.mkdir()
        write_sample(str(l), seed=5)
        write_sample(str(r), seed=6)
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        ldf, rdf = session.read_parquet(str(l)), session.read_parquet(str(r))
        hs.create_index(ldf, hst.CoveringIndexConfig("aJL", ["Query"], ["imprs"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("aJR", ["Query"], ["imprs"]))
        session.enable_hyperspace()
        renamed = DataFrame(Rename({"Query": "q2_alias"}, ldf.plan), session)
        q = renamed.join(rdf, on=hst.col("q2_alias") == hst.col("Query")).select(
            "q2_alias", "imprs"
        )
        assert not index_scans(q)  # rewrite would mis-bind the renamed key
        check_answer(session, q)


class TestGlobbingHybrid:
    """Globbing pattern × appended data × hybrid scan
    (ref: E2EHyperspaceRulesTest:926-985)."""

    def test_glob_pattern_with_appends_hybrid_scan(self, session, hs, tmp_path):
        base = tmp_path / "glob"
        (base / "2024").mkdir(parents=True)
        (base / "2025").mkdir()
        write_sample(str(base / "2024"), seed=7)
        write_sample(str(base / "2025"), seed=8)
        pattern = str(base / "*")
        session.conf.set(hst.keys.GLOBBING_PATTERN, pattern)
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        session.conf.set(hst.keys.LINEAGE_ENABLED, True)
        df = session.read_parquet(str(base))
        hs.create_index(df, hst.CoveringIndexConfig("globIdx", ["Query"], ["imprs"]))
        # append under a NEW glob-matched dir after indexing
        (base / "2026").mkdir()
        write_sample(str(base / "2026"), seed=9, start=1)
        session.conf.set(hst.keys.HYBRID_SCAN_ENABLED, True)
        session.conf.set(hst.keys.HYBRID_SCAN_MAX_APPENDED_RATIO, 0.9)
        try:
            df2 = session.read_parquet(str(base))
            q = df2.filter(hst.col("Query") == "q5").select("imprs")
            session.enable_hyperspace()
            plan = q.optimized_plan()
            assert any(
                isinstance(p, (L.IndexScan, L.BucketUnion)) for p in L.collect(plan, lambda x: True)
            ), plan.pretty()
            check_answer(session, q)
        finally:
            session.conf.set(hst.keys.HYBRID_SCAN_ENABLED, False)
            session.conf.unset(hst.keys.GLOBBING_PATTERN)


def _refresh_then_query_matrix_case(session, hs, make_source, refresh_mode, name):
    """Shared scenario: index -> mutate source -> refreshIndex(mode) ->
    query must use the index and match the no-index answer."""
    df, mutate = make_source()
    hs.create_index(df, hst.CoveringIndexConfig(name, ["k"], ["v"]))
    df2 = mutate()
    if refresh_mode == "quick":
        session.conf.set(hst.keys.HYBRID_SCAN_ENABLED, True)
        session.conf.set(hst.keys.HYBRID_SCAN_MAX_APPENDED_RATIO, 0.99)
    try:
        hs.refresh_index(name, refresh_mode)
        q = df2.filter(hst.col("k") == 3).select("v")
        session.enable_hyperspace()
        plan = q.optimized_plan()
        used = any(
            isinstance(p, (L.IndexScan, L.BucketUnion)) for p in L.collect(plan, lambda x: True)
        )
        assert used, f"{name}/{refresh_mode}: {plan.pretty()}"
        check_answer(session, q)
    finally:
        if refresh_mode == "quick":
            session.conf.set(hst.keys.HYBRID_SCAN_ENABLED, False)


def _table(seed, n=300):
    rng = np.random.default_rng(seed)
    return pa.table(
        {"k": rng.integers(0, 20, n).astype(np.int64), "v": np.round(rng.uniform(0, 10, n), 3)}
    )


class TestRefreshModeSourceMatrix:
    """refresh-then-query per refresh mode × source format
    (ref: RefreshIndexTest, DeltaLakeIntegrationTest, IcebergIntegrationTest)."""

    @pytest.fixture(autouse=True)
    def _buckets(self, session):
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        session.conf.set(hst.keys.LINEAGE_ENABLED, True)

    @pytest.mark.parametrize("mode", ["full", "incremental", "quick"])
    def test_parquet(self, session, hs, tmp_path, mode):
        d = tmp_path / f"pq_{mode}"
        d.mkdir()

        def make():
            pq.write_table(_table(1), d / "p0.parquet")
            df = session.read_parquet(str(d))

            def mutate():
                pq.write_table(_table(2), d / "p1.parquet")
                return session.read_parquet(str(d))

            return df, mutate

        _refresh_then_query_matrix_case(session, hs, make, mode, f"pqM_{mode}")

    @pytest.mark.parametrize("mode", ["full", "incremental", "quick"])
    def test_delta(self, session, hs, tmp_path, mode):
        from hyperspace_tpu.sources.delta import write_delta_table

        d = str(tmp_path / f"dl_{mode}")

        def make():
            write_delta_table(_table(3), d)
            df = session.read_delta(d)

            def mutate():
                write_delta_table(_table(4), d)
                return session.read_delta(d)

            return df, mutate

        _refresh_then_query_matrix_case(session, hs, make, mode, f"dlM_{mode}")

    @pytest.mark.parametrize("mode", ["full", "incremental", "quick"])
    def test_iceberg(self, session, hs, tmp_path, mode):
        from hyperspace_tpu.sources.iceberg import write_iceberg_table

        d = str(tmp_path / f"ib_{mode}")

        def make():
            write_iceberg_table(_table(5), d)
            df = session.read_iceberg(d)

            def mutate():
                write_iceberg_table(_table(6), d)
                return session.read_iceberg(d)

            return df, mutate

        _refresh_then_query_matrix_case(session, hs, make, mode, f"ibM_{mode}")

    def test_incremental_with_deleted_files(self, session, hs, tmp_path):
        """(ref: E2EHyperspaceRulesTest:520 'index usage after incremental
        refresh with some source data file deleted')"""
        d = tmp_path / "pq_del"
        d.mkdir()
        pq.write_table(_table(7), d / "p0.parquet")
        pq.write_table(_table(8), d / "p1.parquet")
        df = session.read_parquet(str(d))
        hs.create_index(df, hst.CoveringIndexConfig("delIdx", ["k"], ["v"]))
        os.remove(d / "p1.parquet")
        hs.refresh_index("delIdx", "incremental")
        df2 = session.read_parquet(str(d))
        q = df2.filter(hst.col("k") == 3).select("v")
        session.enable_hyperspace()
        assert index_scans(q), q.optimized_plan().pretty()
        on = check_answer(session, q)
        want = _table(7).to_pandas()
        assert sorted(on["v"].tolist()) == sorted(
            want[want["k"] == 3]["v"].round(3).tolist()
        )


class TestSignatureInvalidation:
    """FileSignatureFilter behaviors (ref: CandidateIndexCollectorTest:89-303,
    FileSignatureFilter.scala:33-192): any change to the source fileset
    disqualifies a stale index outside hybrid scan, and a refresh
    re-qualifies it."""

    def test_in_place_rewrite_disqualifies(self, session, hs, tmp_path):
        d = tmp_path / "sig1"
        d.mkdir()
        write_sample(str(d))
        df = session.read_parquet(str(d))
        hs.create_index(df, hst.CoveringIndexConfig("sigIdx", ["Query"], ["imprs"]))
        session.enable_hyperspace()
        q0 = df.filter(hst.col("Query") == "q1").select("imprs")
        assert index_scans(q0)
        # rewrite the SAME file name with different content
        write_sample(str(d), seed=99)
        df2 = session.read_parquet(str(d))
        q = df2.filter(hst.col("Query") == "q1").select("imprs")
        assert not index_scans(q), q.optimized_plan().pretty()
        check_answer(session, q)

    def test_deleted_file_without_lineage_disqualifies(self, session, hs, tmp_path):
        d = tmp_path / "sig2"
        d.mkdir()
        write_sample(str(d), seed=1)
        write_sample(str(d), seed=2, start=1)
        df = session.read_parquet(str(d))
        hs.create_index(df, hst.CoveringIndexConfig("sigDel", ["Query"], ["imprs"]))
        os.remove(d / "part-00001.parquet")
        session.enable_hyperspace()
        df2 = session.read_parquet(str(d))
        q = df2.filter(hst.col("Query") == "q1").select("imprs")
        # no lineage: the index cannot subtract the deleted file's rows
        assert not index_scans(q), q.optimized_plan().pretty()
        check_answer(session, q)

    def test_full_refresh_requalifies(self, session, hs, tmp_path):
        d = tmp_path / "sig3"
        d.mkdir()
        write_sample(str(d), seed=3)
        df = session.read_parquet(str(d))
        hs.create_index(df, hst.CoveringIndexConfig("sigRe", ["Query"], ["imprs"]))
        write_sample(str(d), seed=4, start=1)  # append -> stale
        session.enable_hyperspace()
        df2 = session.read_parquet(str(d))
        q = df2.filter(hst.col("Query") == "q1").select("imprs")
        assert not index_scans(q)
        hs.refresh_index("sigRe", "full")
        q2 = session.read_parquet(str(d)).filter(hst.col("Query") == "q1").select("imprs")
        assert index_scans(q2), q2.optimized_plan().pretty()
        check_answer(session, q2)


class TestUnsupportedIndexes:
    """Rules skip indexes of other kinds (ref: E2EHyperspaceRulesTest:1008-1023)."""

    def test_filter_rule_ignores_dataskipping_for_covering_rewrite(self, session, hs, tmp_path):
        d = tmp_path / "unsup"
        d.mkdir()
        write_sample(str(d))
        df = session.read_parquet(str(d))
        hs.create_index(
            df, hst.DataSkippingIndexConfig("dsOnly", hst.MinMaxSketch("imprs"))
        )
        session.enable_hyperspace()
        # no covering index exists: the plan keeps scanning source files
        q = df.filter(hst.col("Query") == "q1").select("imprs")
        assert not index_scans(q)
        check_answer(session, q)
