"""Whole-plan fusion compiler: stage IR, donated fold state, fused join-agg.

Pinned properties:
- fused streamed results are identical to the per-family path with fusion
  off, on q1 (filter→group→agg), q3 (filter→join→group→agg), and top-k
  chains — byte-identical for keys/counts/int aggregates, fp-tolerance for
  float sums (the repo-wide device-vs-host discipline), and byte-identical
  between donation on and off;
- fusion is default-off: a session that never touches the conf dispatches
  zero fused programs;
- one fused executable per (skeleton, shape bucket, mesh fingerprint):
  hs_xla_compiles_total is flat across a chunk-size sweep within warm
  buckets;
- donated fold state really donates: the pre-call state buffer is deleted
  after the fused call (the donated-buffer-reuse regression);
- shapes the fused programs can't run fall back per-family, counted in
  hs_device_fallback_total{op="fusion"}, with unchanged results;
- every fused program satisfies its registered HLO contract (single
  fusion region, zero host callbacks, declared collectives only) when
  verified at program-cache fill under hyperspace.check.hlo.enabled;
- the fused q3 chain folds each chunk in ONE dispatch — a ≥3x
  hs_device_dispatches_total reduction against the per-family
  probe/postjoin/agg-chunk/merge sequence over the same chunks.
"""

import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.exec import trace
from hyperspace_tpu.obs.metrics import REGISTRY

pytestmark = pytest.mark.fusion

FLOAT_RTOL = 1e-9

FUSED_PROGRAMS = (
    "fused-stage-agg",
    "fused-stage-agg-sharded",
    "fused-stage-topk",
    "fused-stage-topk-sharded",
    "fused-stage-join-agg",
)


def _counter(name, **labels) -> float:
    return REGISTRY.counter(name, "", **labels).value


def _fused_dispatches() -> float:
    return sum(_counter("hs_device_dispatches_total", program=p) for p in FUSED_PROGRAMS)


def _fallbacks() -> float:
    snap = REGISTRY.snapshot().get("hs_device_fallback_total")
    if not snap:
        return 0.0
    return sum(s["value"] for s in snap["series"] if s["labels"].get("op") == "fusion")


def _compiles() -> float:
    snap = REGISTRY.snapshot().get("hs_xla_compiles_total")
    if not snap:
        return 0.0
    return sum(s["value"] for s in snap["series"])


def _mk_session(tmp_path, tag="s", fusion=None, donation=True, **conf):
    base = {
        hst.keys.SYSTEM_PATH: str(tmp_path / f"idx_{tag}"),
        hst.keys.TPU_QUERY_DEVICE_EXECUTION: True,
        hst.keys.TPU_QUERY_DEVICE_MIN_ROWS: 0,
        hst.keys.EXEC_STREAM_AGG_MIN_BYTES: 1,
        hst.keys.EXEC_STREAM_CHUNK_BYTES: 1,  # one file per chunk
    }
    base.update(conf)
    if fusion is not None:
        base[hst.keys.EXEC_FUSION_ENABLED] = fusion
        base[hst.keys.EXEC_FUSION_DONATION] = donation
    sess = hst.Session(conf=base)
    hst.set_session(sess)
    return sess


def _write_q1(d, num_files=4, rows=700, seed=7, string_key=False, null_float_key=False):
    os.makedirs(d, exist_ok=True)
    rng = np.random.default_rng(seed)
    for i in range(num_files):
        n = rows + 37 * i  # different shapes exercise the bucket padding
        cols = {
            "g": rng.integers(0, 9, n).astype(np.int64),
            "qty": rng.integers(0, 500, n).astype(np.int64),
            "price": np.round(rng.uniform(0, 1000, n), 3),
        }
        if string_key:
            s = np.array([f"c{v}" for v in rng.integers(0, 5, n)], dtype=object)
            s[rng.random(n) < 0.03] = None
            cols["s"] = s
        if null_float_key:
            f = np.round(rng.uniform(-5, 5, n), 2)
            f[rng.random(n) < 0.05] = np.nan
            f[rng.random(n) < 0.05] = -0.0
            cols["fk"] = f
        pq.write_table(pa.table(cols), os.path.join(d, f"p{i}.parquet"))
    return d


def _write_q3(d, num_files=4, rows=900, build_rows=120, seed=3):
    probe, build = os.path.join(d, "probe"), os.path.join(d, "build")
    os.makedirs(probe, exist_ok=True)
    os.makedirs(build, exist_ok=True)
    rng = np.random.default_rng(seed)
    for i in range(num_files):
        pq.write_table(pa.table({
            "k": rng.integers(0, 80, rows).astype(np.int64),
            "g": rng.integers(0, 12, rows).astype(np.int64),
            "v": np.round(rng.standard_normal(rows), 4),
        }), os.path.join(probe, f"p{i}.parquet"))
    pq.write_table(pa.table({
        "k2": rng.integers(0, 90, build_rows).astype(np.int64),
        "w": np.round(rng.standard_normal(build_rows), 4),
    }), os.path.join(build, "b.parquet"))
    return probe, build


def _q1(df, key="g"):
    return (
        df.filter(hst.col("qty") > 40)
        .group_by(key)
        .agg(
            n=("*", "count"),
            sq=("qty", "sum"),
            sp=("price", "sum"),
            aq=("qty", "avg"),
            lo=("price", "min"),
            hi=("qty", "max"),
            sd=("price", "stddev_samp"),
        )
    )


def _q3(sess, probe_dir, build_dir):
    probe = sess.read_parquet(probe_dir)
    build = sess.read_parquet(build_dir)
    return (
        probe.join(build, on=hst.col("k") == hst.col("k2"), how="inner")
        .filter(hst.col("v") > -0.5)
        .group_by("g")
        .agg(n=("*", "count"), s=("v", "sum"), a=("w", "avg"),
             mn=("v", "min"), mx=("w", "max"))
    )


def _sorted_by(got, *keys):
    arrays = [np.asarray(got[k]) for k in keys]
    order = np.lexsort(tuple(reversed(arrays)))
    return {c: np.asarray(v)[order] for c, v in got.items()}


def assert_results_equal(got, want, float_cols=(), sort_keys=()):
    if sort_keys:
        got, want = _sorted_by(got, *sort_keys), _sorted_by(want, *sort_keys)
    assert sorted(got.keys()) == sorted(want.keys())
    for k in got:
        a, b = np.asarray(got[k]), np.asarray(want[k])
        assert a.shape == b.shape, k
        if k in float_cols:
            np.testing.assert_allclose(a, b, rtol=FLOAT_RTOL, equal_nan=True, err_msg=k)
        elif a.dtype == object or b.dtype == object:
            assert all(
                (not isinstance(x, str) and not isinstance(y, str)) or x == y
                for x, y in zip(a, b)
            ), k
        else:
            assert a.tobytes() == b.tobytes(), k


# --------------------------------------------------------------------------
# q1: fused grouped-agg stream vs the per-family stream
# --------------------------------------------------------------------------


class TestQ1Fused:
    def test_fused_byte_identical_to_per_family_stream(self, tmp_path):
        data = _write_q1(str(tmp_path / "q1"))
        sess = _mk_session(tmp_path, "off", fusion=False)
        with trace.recording() as ev_off:
            want = _q1(sess.read_parquet(data)).collect()
        assert ("agg", "device-grouped-stream") in ev_off
        sess = _mk_session(tmp_path, "on", fusion=True)
        d0, f0 = _fused_dispatches(), _counter(
            "hs_device_dispatches_total", program="grouped-agg-chunk"
        )
        with trace.recording() as ev_on:
            got = _q1(sess.read_parquet(data)).collect()
        assert ("agg", "device-grouped-stream") in ev_on
        assert _fused_dispatches() - d0 >= 4  # one fused dispatch per chunk
        # no per-family grouped-chunk dispatches on the fused stream
        assert _counter("hs_device_dispatches_total", program="grouped-agg-chunk") == f0
        # both are device streamed folds: identical to the byte
        for k in want:
            assert np.asarray(got[k]).tobytes() == np.asarray(want[k]).tobytes(), k

    def test_donation_on_off_byte_identical(self, tmp_path):
        data = _write_q1(str(tmp_path / "q1"))
        sess = _mk_session(tmp_path, "don", fusion=True, donation=True)
        got_d = _q1(sess.read_parquet(data)).collect()
        sess = _mk_session(tmp_path, "nodon", fusion=True, donation=False)
        got_n = _q1(sess.read_parquet(data)).collect()
        for k in got_d:
            assert np.asarray(got_d[k]).tobytes() == np.asarray(got_n[k]).tobytes(), k

    def test_null_and_signed_zero_float_group_keys(self, tmp_path):
        data = _write_q1(str(tmp_path / "q1"), null_float_key=True)
        sess = _mk_session(tmp_path, "off", fusion=False)
        want = _q1(sess.read_parquet(data), key="fk").collect()
        sess = _mk_session(tmp_path, "on", fusion=True)
        d0 = _fused_dispatches()
        got = _q1(sess.read_parquet(data), key="fk").collect()
        assert _fused_dispatches() > d0
        for k in want:
            assert np.asarray(got[k]).tobytes() == np.asarray(want[k]).tobytes(), k

    def test_string_group_keys_stay_per_family(self, tmp_path):
        data = _write_q1(str(tmp_path / "q1"), string_key=True)
        sess = _mk_session(tmp_path, "off", fusion=False)
        want = _q1(sess.read_parquet(data), key="s").collect()
        sess = _mk_session(tmp_path, "on", fusion=True)
        d0 = _fused_dispatches()
        got = _q1(sess.read_parquet(data), key="s").collect()
        assert _fused_dispatches() == d0  # string keys never enter the fused path
        assert_results_equal(got, want)

    def test_default_off_identity(self, tmp_path):
        """An untouched session runs zero fused programs and produces the
        same result as a fused session — flipping the default on can never
        change answers."""
        data = _write_q1(str(tmp_path / "q1"))
        sess = _mk_session(tmp_path, "default")  # fusion conf never touched
        assert sess.conf.fusion_enabled is False
        d0 = _fused_dispatches()
        want = _q1(sess.read_parquet(data)).collect()
        assert _fused_dispatches() == d0
        sess = _mk_session(tmp_path, "on", fusion=True)
        got = _q1(sess.read_parquet(data)).collect()
        for k in want:
            assert np.asarray(got[k]).tobytes() == np.asarray(want[k]).tobytes(), k

    def test_capacity_overflow_falls_back_per_chunk_then_resumes(self, tmp_path):
        """A chunk that discovers more groups than the compiled capacity
        redoes per-family (hs_device_fallback_total{op='fusion'}) and the
        stream resumes fused — results unchanged."""
        data = _write_q1(str(tmp_path / "q1"), rows=1200)
        # fused run FIRST: the process-global capacity-hint memo is cold, so
        # the floor-of-2 capacity undershoots chunk 0's 9 groups → overflow
        sess = _mk_session(
            tmp_path, "on", fusion=True,
            **{hst.keys.EXEC_AGG_CAPACITY_FLOOR: 2},
        )
        fb0, d0 = _fallbacks(), _fused_dispatches()
        got = _q1(sess.read_parquet(data)).collect()
        assert _fallbacks() > fb0
        assert _fused_dispatches() > d0  # later chunks still fused
        sess = _mk_session(tmp_path, "off", fusion=False)
        want = _q1(sess.read_parquet(data)).collect()
        assert_results_equal(
            got, want, float_cols=("sp", "aq", "lo", "sd"), sort_keys=("g",)
        )


# --------------------------------------------------------------------------
# compile-count flatness
# --------------------------------------------------------------------------


class TestCompileFlatness:
    def test_chunk_size_sweep_reuses_fused_programs(self, tmp_path):
        """Chunks padding into warm shape buckets compile nothing new: the
        fused program is keyed on (skeleton, shape bucket, mesh), not row
        count."""
        d1 = _write_q1(str(tmp_path / "a"), num_files=3, rows=700, seed=1)
        sess = _mk_session(tmp_path, "warm", fusion=True)
        _q1(sess.read_parquet(d1)).collect()  # warm the buckets
        c0 = _compiles()
        # same schema, same √2 buckets (rows pad to the same capacities)
        d2 = _write_q1(str(tmp_path / "b"), num_files=3, rows=701, seed=2)
        got = _q1(sess.read_parquet(d2)).collect()
        assert _compiles() == c0, "fused program recompiled inside a warm bucket"
        assert len(np.asarray(got["g"])) > 0


# --------------------------------------------------------------------------
# donation really donates
# --------------------------------------------------------------------------


class TestDonation:
    def test_donated_state_buffer_is_deleted(self):
        import jax
        import jax.numpy as jnp

        from hyperspace_tpu.exec import stage_ir

        jitted = stage_ir.compile_stage(
            "test-donation[regression]", lambda s, c: s + c, donate_argnums=(0,)
        )
        state = jax.device_put(jnp.zeros(64, dtype=jnp.int64))
        out = jitted(state, jnp.ones(64, dtype=jnp.int64))
        assert state.is_deleted(), "donate_argnums did not consume the state"
        assert int(out.sum()) == 64

    def test_stage_cache_reuses_compiled_program(self):
        from hyperspace_tpu.exec import stage_ir

        fn = lambda s, c: s + c  # noqa: E731
        a = stage_ir.compile_stage("test-donation[cache]", fn, donate_argnums=(0,))
        b = stage_ir.compile_stage("test-donation[cache]", fn, donate_argnums=(0,))
        assert a is b
        c = stage_ir.compile_stage("test-donation[cache]", fn)
        assert c is not a  # donation vector is part of the cache key

    def test_peak_bytes_gauge_tracks_high_water(self, tmp_path):
        data = _write_q1(str(tmp_path / "q1"))
        sess = _mk_session(tmp_path, "on", fusion=True)
        _q1(sess.read_parquet(data)).collect()
        assert REGISTRY.gauge("hs_device_peak_bytes", "").value > 0


# --------------------------------------------------------------------------
# q3: whole-plan fused join-agg
# --------------------------------------------------------------------------


class TestQ3Fused:
    def test_fused_matches_classic_and_reduces_dispatches(self, tmp_path):
        probe_dir, build_dir = _write_q3(str(tmp_path / "q3"))
        sess = _mk_session(tmp_path, "off", fusion=False)
        want = _q3(sess, probe_dir, build_dir).collect()

        # per-family baseline over the SAME chunks: the dispatch sequence
        # the fused program replaces — hash-probe + post-join filter via
        # the streaming broadcast join, grouped chunk + merge via the
        # per-family GroupedAggStream
        from hyperspace_tpu.exec import device as D
        from hyperspace_tpu.exec.executor import Executor

        base0 = sum(
            s["value"]
            for s in (REGISTRY.snapshot().get("hs_device_dispatches_total") or {"series": []})["series"]
        )
        gs = D.GroupedAggStream(
            sess, ["g"],
            [("n", "count", None), ("s", "sum", "v"), ("a", "avg", "w"),
             ("mn", "min", "v"), ("mx", "max", "w")],
            max_groups=sess.conf.agg_max_groups,
            cap_floor=sess.conf.agg_capacity_floor,
        )
        probe = sess.read_parquet(probe_dir)
        build = sess.read_parquet(build_dir)
        joined = (
            probe.join(build, on=hst.col("k") == hst.col("k2"), how="inner")
            .filter(hst.col("v") > -0.5)
        )
        for chunk in Executor(sess).execute_stream(joined.plan):
            gs.update({c: np.asarray(v) for c, v in chunk.items()}, None)
        perfam = gs.finalize()
        perfam_dispatches = sum(
            s["value"]
            for s in REGISTRY.snapshot()["hs_device_dispatches_total"]["series"]
        ) - base0

        sess = _mk_session(tmp_path, "on", fusion=True)
        d0 = _counter("hs_device_dispatches_total", program="fused-stage-join-agg")
        base1 = sum(
            s["value"]
            for s in REGISTRY.snapshot()["hs_device_dispatches_total"]["series"]
        )
        with trace.recording() as events:
            got = _q3(sess, probe_dir, build_dir).collect()
        assert ("agg", "fused-join-agg-stream") in events
        fused_total = sum(
            s["value"]
            for s in REGISTRY.snapshot()["hs_device_dispatches_total"]["series"]
        ) - base1
        assert _counter(
            "hs_device_dispatches_total", program="fused-stage-join-agg"
        ) - d0 >= 4  # one per probe chunk

        # ≥3x fewer dispatches than the per-family program sequence
        assert perfam_dispatches >= 3 * fused_total, (perfam_dispatches, fused_total)

        float_cols = ("s", "a", "mn", "mx")
        assert_results_equal(got, want, float_cols=float_cols, sort_keys=("g",))
        assert_results_equal(got, perfam, float_cols=float_cols, sort_keys=("g",))

    def test_donation_on_off_identical(self, tmp_path):
        probe_dir, build_dir = _write_q3(str(tmp_path / "q3"))
        sess = _mk_session(tmp_path, "don", fusion=True, donation=True)
        got_d = _q3(sess, probe_dir, build_dir).collect()
        sess = _mk_session(tmp_path, "nodon", fusion=True, donation=False)
        got_n = _q3(sess, probe_dir, build_dir).collect()
        got_d, got_n = _sorted_by(got_d, "g"), _sorted_by(got_n, "g")
        for k in got_d:
            assert np.asarray(got_d[k]).tobytes() == np.asarray(got_n[k]).tobytes(), k

    def test_string_group_key_falls_back_counted(self, tmp_path):
        """A q3 chain grouped by a string key cannot fuse: the fallback is
        counted in hs_device_fallback_total{op='fusion'} and the classic
        path answers, unchanged."""
        probe_dir, build_dir = _write_q3(str(tmp_path / "q3"))
        # rewrite the probe side with a string group column
        rng = np.random.default_rng(5)
        for i, f in enumerate(sorted(os.listdir(probe_dir))):
            t = pq.read_table(os.path.join(probe_dir, f))
            n = t.num_rows
            t = t.append_column(
                "gs", pa.array([f"s{v}" for v in rng.integers(0, 6, n)])
            )
            pq.write_table(t, os.path.join(probe_dir, f))

        def q(sess):
            probe = sess.read_parquet(probe_dir)
            build = sess.read_parquet(build_dir)
            return (
                probe.join(build, on=hst.col("k") == hst.col("k2"), how="inner")
                .group_by("gs")
                .agg(n=("*", "count"), s=("v", "sum"))
            )

        sess = _mk_session(tmp_path, "off", fusion=False)
        want = q(sess).collect()
        sess = _mk_session(tmp_path, "on", fusion=True)
        fb0, d0 = _fallbacks(), _counter(
            "hs_device_dispatches_total", program="fused-stage-join-agg"
        )
        got = q(sess).collect()
        assert _fallbacks() > fb0
        assert _counter(
            "hs_device_dispatches_total", program="fused-stage-join-agg"
        ) == d0
        assert_results_equal(got, want, float_cols=("s",), sort_keys=("gs",))


# --------------------------------------------------------------------------
# top-k: fused select+merge
# --------------------------------------------------------------------------


def _write_topk(d, num_files=5, rows=600, seed=13):
    os.makedirs(d, exist_ok=True)
    rng = np.random.default_rng(seed)
    for i in range(num_files):
        v = np.round(rng.uniform(-100, 100, rows), 3)
        v[rng.choice(rows, 10, replace=False)] = np.nan
        name = np.array([f"n{j % 17:02d}" for j in range(rows)], dtype=object)
        pq.write_table(pa.table({
            "k": rng.integers(0, 5000, rows).astype(np.int64),
            "v": v,
            "name": name,
        }), os.path.join(d, f"p{i}.parquet"))
    return d


class TestTopkFused:
    def test_fused_byte_identical_multi_key_nan(self, tmp_path):
        data = _write_topk(str(tmp_path / "tk"))
        q = lambda df: df.order_by("v", "k", ascending=[False, True]).limit(25)  # noqa: E731
        sess = _mk_session(tmp_path, "off", fusion=False)
        want = q(sess.read_parquet(data)).collect()
        sess = _mk_session(tmp_path, "on", fusion=True)
        d0 = _counter("hs_device_dispatches_total", program="fused-stage-topk")
        with trace.recording() as events:
            got = q(sess.read_parquet(data)).collect()
        assert ("topk", "device-topk-stream") in events
        # chunk 2..n fold fused (the first chunk has no state to merge into)
        assert _counter(
            "hs_device_dispatches_total", program="fused-stage-topk"
        ) - d0 >= 4
        for k in want:
            assert np.asarray(got[k]).tobytes() == np.asarray(want[k]).tobytes(), k

    def test_donation_on_off_byte_identical(self, tmp_path):
        data = _write_topk(str(tmp_path / "tk"))
        q = lambda df: df.order_by("v", ascending=[False]).limit(40)  # noqa: E731
        sess = _mk_session(tmp_path, "don", fusion=True, donation=True)
        got_d = q(sess.read_parquet(data)).collect()
        sess = _mk_session(tmp_path, "nodon", fusion=True, donation=False)
        got_n = q(sess.read_parquet(data)).collect()
        for k in got_d:
            assert np.asarray(got_d[k]).tobytes() == np.asarray(got_n[k]).tobytes(), k

    def test_string_keys_stay_per_family(self, tmp_path):
        data = _write_topk(str(tmp_path / "tk"))
        q = lambda df: df.order_by("name", "k").limit(20)  # noqa: E731
        sess = _mk_session(tmp_path, "off", fusion=False)
        want = q(sess.read_parquet(data)).collect()
        sess = _mk_session(tmp_path, "on", fusion=True)
        d0 = _counter("hs_device_dispatches_total", program="fused-stage-topk")
        got = q(sess.read_parquet(data)).collect()
        # string keys need the host re-encode between select and merge
        assert _counter(
            "hs_device_dispatches_total", program="fused-stage-topk"
        ) == d0
        assert_results_equal(got, want)


# --------------------------------------------------------------------------
# sharded twins
# --------------------------------------------------------------------------


class TestShardedFused:
    def test_sharded_fused_grouped_agg_matches_per_family_sharded(self, tmp_path):
        """Fused vs per-family on the SAME topology is byte-identical (same
        shard-local fold order); sharded vs single-device floats compare to
        tolerance — the established mesh-exec discipline (shard-local sums
        reassociate float addition)."""
        data = _write_q1(str(tmp_path / "q1"), rows=900)
        shard_conf = {hst.keys.PARALLEL_ENABLED: True, hst.keys.PARALLEL_MIN_ROWS: 0}
        sess = _mk_session(tmp_path, "shoff", fusion=False, **shard_conf)
        want = _q1(sess.read_parquet(data)).collect()
        sess = _mk_session(tmp_path, "shon", fusion=True, **shard_conf)
        d0 = _counter("hs_device_dispatches_total", program="fused-stage-agg-sharded")
        got = _q1(sess.read_parquet(data)).collect()
        assert _counter(
            "hs_device_dispatches_total", program="fused-stage-agg-sharded"
        ) > d0
        for k in want:
            assert np.asarray(got[k]).tobytes() == np.asarray(want[k]).tobytes(), k
        sess = _mk_session(tmp_path, "single", fusion=True)
        single = _q1(sess.read_parquet(data)).collect()
        assert_results_equal(
            got, single, float_cols=("sp", "aq", "lo", "sd"), sort_keys=("g",)
        )

    def test_sharded_fused_topk_matches_single_device(self, tmp_path):
        data = _write_topk(str(tmp_path / "tk"))
        q = lambda df: df.order_by("v", "k", ascending=[False, True]).limit(30)  # noqa: E731
        sess = _mk_session(tmp_path, "single", fusion=True)
        want = q(sess.read_parquet(data)).collect()
        sess = _mk_session(
            tmp_path, "sharded", fusion=True,
            **{hst.keys.PARALLEL_ENABLED: True, hst.keys.PARALLEL_MIN_ROWS: 0},
        )
        d0 = _counter("hs_device_dispatches_total", program="fused-stage-topk-sharded")
        got = q(sess.read_parquet(data)).collect()
        assert _counter(
            "hs_device_dispatches_total", program="fused-stage-topk-sharded"
        ) > d0
        for k in want:
            assert np.asarray(got[k]).tobytes() == np.asarray(want[k]).tobytes(), k


# --------------------------------------------------------------------------
# HLO contracts at program-cache fill
# --------------------------------------------------------------------------


class TestHloContracts:
    def test_fused_programs_verify_clean(self, tmp_path):
        from hyperspace_tpu.check import hlo_lint

        hlo_lint.reset_runtime_state()
        data = _write_q1(str(tmp_path / "q1"), seed=29)
        probe_dir, build_dir = _write_q3(str(tmp_path / "q3"), seed=31)
        tk = _write_topk(str(tmp_path / "tk"), seed=37)
        sess = _mk_session(
            tmp_path, "hlo", fusion=True,
            **{hst.keys.CHECK_HLO_ENABLED: True},
        )
        v0 = _counter("hs_check_programs_verified_total", program="fused-stage-agg")
        _q1(sess.read_parquet(data)).collect()
        _q3(sess, probe_dir, build_dir).collect()
        sess.read_parquet(tk).order_by("v", ascending=[False]).limit(25).collect()
        assert _counter(
            "hs_check_programs_verified_total", program="fused-stage-agg"
        ) > v0
        bad = hlo_lint.runtime_violations()
        assert bad == [], "\n".join(f.render() for f in bad)
