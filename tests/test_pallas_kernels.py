"""Pallas kernel numerics (interpret mode on the CPU backend).

The kernels are the device programs behind MinMaxSketch builds and bucketed
write planning (ops/kernels.py); off-TPU they run in the pallas interpreter
with identical numerics.
"""

import numpy as np
import pytest

from hyperspace_tpu.ops.kernels import bucket_histogram, segmented_min_max


def test_segmented_min_max_matches_numpy():
    rng = np.random.default_rng(0)
    segs = [rng.standard_normal(int(rng.integers(1, 700))) for _ in range(13)]
    mins, maxs = segmented_min_max(segs)
    for i, s in enumerate(segs):
        assert mins[i] == s.min()
        assert maxs[i] == s.max()


def test_segmented_min_max_nulls_and_empty():
    segs = [np.array([1.0, np.nan, -3.0]), np.array([]), np.array([np.nan])]
    mins, maxs = segmented_min_max(segs)
    assert mins[0] == -3.0 and maxs[0] == 1.0
    assert np.isnan(mins[1]) and np.isnan(maxs[1])
    assert np.isnan(mins[2]) and np.isnan(maxs[2])


def test_segmented_min_max_int_segments():
    segs = [np.arange(100, dtype=np.int64), np.array([7], dtype=np.int64)]
    mins, maxs = segmented_min_max(segs)
    assert mins[0] == 0 and maxs[0] == 99
    assert mins[1] == 7 and maxs[1] == 7


@pytest.mark.parametrize("n,nb", [(10_000, 64), (5, 8), (2048, 128), (3000, 200)])
def test_bucket_histogram_matches_bincount(n, nb):
    rng = np.random.default_rng(n)
    b = rng.integers(0, nb, n)
    assert np.array_equal(bucket_histogram(b, nb), np.bincount(b, minlength=nb))


def test_bucket_histogram_empty():
    assert np.array_equal(bucket_histogram(np.array([], dtype=np.int64), 8), np.zeros(8, np.int32))


def test_minmax_sketch_build_uses_exact_int_bounds(tmp_path):
    """End-to-end: DataSkippingIndex MinMax rows equal the host oracle."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    import hyperspace_tpu as hst
    from hyperspace_tpu.indexes.dataskipping import DataSkippingIndexConfig, MinMaxSketch

    rng = np.random.default_rng(5)
    root = tmp_path / "data"
    root.mkdir()
    expected = []
    for i in range(5):
        vals = rng.integers(-(10**9), 10**9, 500).astype(np.int64)
        expected.append((int(vals.min()), int(vals.max())))
        pq.write_table(pa.table({"k": vals}), root / f"f{i}.parquet")

    sess = hst.Session(conf={hst.keys.SYSTEM_PATH: str(tmp_path / "idx")})
    hst.set_session(sess)
    try:
        hs = hst.Hyperspace(sess)
        df = sess.read_parquet(str(root))
        hs.create_index(df, DataSkippingIndexConfig("mm", MinMaxSketch("k")))
        entry = sess.index_manager.get_index("mm")
        from hyperspace_tpu.indexes.registry import index_of_entry

        idx = index_of_entry(entry)
        table = idx.read_sketch_table(entry)
        mins = table.column("MinMax_k__min").to_pylist()
        maxs = table.column("MinMax_k__max").to_pylist()
        assert sorted(zip(mins, maxs)) == sorted(expected)
    finally:
        hst.set_session(None)
